"""Broker: replicated (and stateless-restartable) membership registry.

Counterpart of the reference's ``BrokerService`` (``src/broker.h:99-237``) and
broker CLI (``py/moolib/broker.py:21-40``).  Peers ping the broker with their
group name; the broker evicts peers whose pings stop, and on any membership
change bumps the group's epoch (``sync_id``) and pushes the new sorted member
list to every member.  Allreduce epochs are keyed by ``sync_id``, which is
what makes the whole stack elastic: a pushed update cancels in-flight
reductions on the clients (see ``moolib_tpu.group``).

High availability (docs/RESILIENCE.md "Broker failover"): a **primary**
broker replicates every group's state (members, observers, hosts,
``sync_id``) to hot-standby brokers via ``__broker_replicate`` on the
``update()`` cadence.  A standby that stops hearing from its primary for
``promote_grace`` seconds promotes itself on the first member ping it
receives, bumping a monotonic **generation**.  The generation rides in every
ping reply, epoch push, and replication frame and acts as a split-brain
fence: a zombie ex-primary that comes back (process un-wedges, partition
heals) sees a higher generation — in a peer ping or in replication from the
new primary — and demotes itself to standby; peers reject its stale epoch
pushes by the same fence.  Generation ties (two standbys promoted during the
same chaos window) break deterministically on the broker name, so exactly
one primary survives any heal.

Run standalone with ``python -m moolib_tpu.broker`` (``--brokers`` with the
full address list + ``--standby`` for the hot spares).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from . import telemetry, utils
from .rpc import Rpc

_REG = telemetry.get_registry()
_M_SYNC_REPAIRS = _REG.counter(
    "broker_sync_id_repairs_total",
    "Higher client sync_id absorbed by the broker (restarted-broker / "
    "clock-skew epoch repair; each one is a cohort that outran this broker)",
)
_M_PROMOTIONS = _REG.counter(
    "broker_promotions_total", "Standby-to-primary takeovers (generation bumps)"
)
_M_DEMOTIONS = _REG.counter(
    "broker_demotions_total",
    "Primary-to-standby demotions (zombie fenced by a higher generation)",
)
_M_REPL_APPLIED = _REG.counter(
    "broker_replications_total", "Replication snapshots applied from a primary"
)
_M_REPL_REJECTS = _REG.counter(
    "broker_replication_rejects_total",
    "Replication snapshots rejected for carrying a stale generation",
)
_M_GENERATION = _REG.gauge(
    "broker_generation", "This broker's current generation fence value"
)
_M_IS_PRIMARY = _REG.gauge(
    "broker_is_primary", "1 while this broker is the serving primary, else 0"
)


class _BrokerGroup:
    __slots__ = ("name", "members", "observers", "sync_id", "active_members",
                 "active_hosts", "needs_update", "last_update")

    def __init__(self, name: str):
        self.name = name
        # peer name -> {"last_ping": t, "sort_order": int, "host": str|None}
        self.members: Dict[str, dict] = {}
        # Non-contributing members (serving replicas, observers): registered
        # for liveness + discovery (``__broker_list``) but NEVER part of the
        # membership epoch — joining, leaving, or dying must not bump
        # ``sync_id`` (an epoch bump cancels the cohort's in-flight
        # reductions; a serving replica must not be able to do that).
        # peer name -> {"last_ping": t, "role": str}
        self.observers: Dict[str, dict] = {}
        self.sync_id = int(time.time() * 1000) % (1 << 40)
        self.active_members: list = []
        # Host map SNAPSHOTTED at the epoch bump: resync must serve exactly
        # what the epoch push served (ring_auto input, wire protocol), not a
        # live view that may have mutated inside the bump rate-limit window.
        self.active_hosts: Dict[str, Optional[str]] = {}
        self.needs_update = False
        self.last_update = 0.0


class Broker:
    """Coordinates a cohort during training (same API as the reference)."""

    def __init__(self, rpc: Optional[Rpc] = None, standby: bool = False):
        self._rpc = rpc if rpc is not None else Rpc()
        self._groups: Dict[str, _BrokerGroup] = {}
        self._timeout = 10.0
        # _on_ping/_on_resync run on the Rpc handler thread pool, concurrently
        # with update() on the caller thread; all group/member/sync_id state is
        # guarded here (push RPCs are issued outside the lock).
        self._lock = threading.Lock()
        # --- high availability ------------------------------------------
        # The generation fence: bumped on every standby takeover, carried in
        # ping replies, epoch pushes, and replication; higher wins, ties
        # break on the broker name (deterministic single survivor).
        self._generation = 1
        self._primary = not standby
        self._peer_broker_addrs: List[str] = []
        self._replicate_interval = 0.5
        self._last_replicate_tx = 0.0
        # Standby promotion clock: how long since the primary last proved it
        # was alive (a replication snapshot landed here).  Seeded with "now"
        # so a freshly-started standby gives the primary one full grace
        # window before it will take over.
        self._last_replicate_rx = time.monotonic()
        self._promote_grace = 3.0
        self._rpc.define("__broker_ping", self._on_ping)
        self._rpc.define("__broker_resync", self._on_resync)
        self._rpc.define("__broker_leave", self._on_leave)
        self._rpc.define("__broker_list", self._on_list)
        self._rpc.define("__broker_replicate", self._on_replicate)
        self._rpc.define("__broker_status", self._on_status)
        _M_GENERATION.set(self._generation)
        _M_IS_PRIMARY.set(1.0 if self._primary else 0.0)

    # transparent passthroughs ------------------------------------------------
    def set_name(self, name: str) -> None:
        self._rpc.set_name(name)

    def connect(self, address: str) -> None:
        """Connect the broker's Rpc to an existing peer/network (reference
        ``Broker`` passthrough, ``src/broker.h:240-265``)."""
        self._rpc.connect(address)

    def listen(self, address: str) -> None:
        self._rpc.listen(address)

    def set_timeout(self, seconds: float) -> None:
        self._timeout = float(seconds)

    @property
    def rpc(self) -> Rpc:
        return self._rpc

    # high-availability api ---------------------------------------------------
    def set_peer_brokers(self, addresses: Sequence[str]) -> None:
        """Addresses of the OTHER brokers in this control plane.  A primary
        replicates group state to them every ``replicate_interval``; a
        standby expects replication FROM one of them and promotes itself
        when it goes quiet past ``promote_grace``."""
        self._peer_broker_addrs = [a for a in addresses if a]
        for a in self._peer_broker_addrs:
            self._rpc.connect(a)

    def set_replicate_interval(self, seconds: float) -> None:
        self._replicate_interval = float(seconds)

    def set_promote_grace(self, seconds: float) -> None:
        """How long a standby waits after the last replication snapshot
        before a member ping makes it take over as primary."""
        self._promote_grace = float(seconds)

    @property
    def is_primary(self) -> bool:
        return self._primary

    @property
    def generation(self) -> int:
        return self._generation

    def _promote_locked(self, now: float) -> None:
        """Standby takeover: bump the generation fence and re-publish every
        replicated group as a fresh epoch.  Members get a full ping window
        (their replicated liveness stamps are re-stamped at apply/promote
        time) before eviction can touch them."""
        self._primary = True
        self._generation += 1
        _M_PROMOTIONS.inc()
        _M_GENERATION.set(self._generation)
        _M_IS_PRIMARY.set(1.0)
        for g in self._groups.values():
            for m in g.members.values():
                m["last_ping"] = now
            for o in g.observers.values():
                o["last_ping"] = now
            if g.members:
                g.needs_update = True
                g.last_update = 0.0  # bypass the churn rate limit: push now
        utils.log_info(
            "broker %s: promoted to primary, generation=%d (groups: %s)",
            self._rpc.get_name(), self._generation, sorted(self._groups),
        )

    def _demote_locked(self, seen_generation: int, why: str) -> None:
        """Generation fence tripped: somebody with a higher (or tie-winning)
        generation is primary.  Become a standby; the winner's replication
        stream will overwrite our group state."""
        if self._primary:
            _M_DEMOTIONS.inc()
            utils.log_info(
                "broker %s: demoted to standby (%s, generation %d -> %d)",
                self._rpc.get_name(), why, self._generation, seen_generation,
            )
        self._primary = False
        self._generation = max(self._generation, int(seen_generation))
        # Restart the promotion clock: don't instantly take back over.
        self._last_replicate_rx = time.monotonic()
        _M_GENERATION.set(self._generation)
        _M_IS_PRIMARY.set(0.0)

    # service -----------------------------------------------------------------
    def _standby_reply(self) -> dict:
        return {
            "sync_id": None,
            "timeout": self._timeout,
            "generation": self._generation,
            "standby": True,
        }

    def _on_ping(self, group_name: str, peer_name: str, sort_order: int, client_sync_id,
                 host: Optional[str] = None, role: str = "member",
                 generation: Optional[int] = None):
        now = time.monotonic()
        with self._lock:
            if generation is not None and generation > self._generation:
                if self._primary and self._peer_broker_addrs:
                    # Zombie fence: a peer already follows a higher-generation
                    # primary — we lost a takeover we never saw.  Stand down;
                    # the peer's failover scan will route it to the winner.
                    self._demote_locked(generation, f"peer {peer_name} pinged gen {generation}")
                    return self._standby_reply()
                # No other broker exists (legacy single-broker deployment, or
                # a fresh restart of the only broker): absorb the generation
                # instead of wedging the cohort behind an unreachable fence.
                self._generation = int(generation)
                _M_GENERATION.set(self._generation)
            if not self._primary:
                if now - self._last_replicate_rx <= self._promote_grace:
                    return self._standby_reply()
                # The primary has been silent past the grace window and a
                # member is knocking: take over.
                self._promote_locked(now)
            g = self._groups.setdefault(group_name, _BrokerGroup(group_name))
            if role != "member":
                # Observer ping: track liveness/role only.  If the peer was
                # previously a contributing member (role change mid-life),
                # it leaves the epoch like any other departure.
                g.observers[peer_name] = {
                    "last_ping": now, "role": str(role),
                }
                if peer_name in g.members:
                    del g.members[peer_name]
                    g.needs_update = True
                return {"sync_id": g.sync_id, "timeout": self._timeout,
                        "generation": self._generation}
            g.observers.pop(peer_name, None)
            # Stateless restart safety: clients ignore epoch pushes that don't
            # EXCEED their current sync_id, so a freshly-restarted broker must
            # jump past any epoch still alive in the cohort. Wall-clock seeding
            # usually guarantees that; a pinged-in higher sync_id (clock skew,
            # regressed clock — or a generation takeover, where the new
            # primary must outrun epochs the old one minted) covers the rest.
            if client_sync_id is not None and client_sync_id > g.sync_id:
                _M_SYNC_REPAIRS.inc()
                utils.log_info(
                    "broker %s: WARNING sync_id repair in group %s: client %s "
                    "pinged %d > broker %d (restart/skew/takeover) — jumping past it",
                    self._rpc.get_name(), group_name, peer_name,
                    int(client_sync_id), g.sync_id,
                )
                g.sync_id = int(client_sync_id) + 1
                g.needs_update = True
            m = g.members.get(peer_name)
            if m is None:
                g.members[peer_name] = {
                    "last_ping": now, "sort_order": sort_order, "host": host,
                }
                g.needs_update = True
            else:
                m["last_ping"] = now
                m["sort_order"] = sort_order
                if m.get("host") != host:
                    # A member's machine changed (same-name restart elsewhere
                    # within the ping timeout): the host map is part of the
                    # epoch contract (ring_auto input), so it must reach the
                    # cohort via a push — never by silent divergence.
                    m["host"] = host
                    g.needs_update = True
            return {"sync_id": g.sync_id, "timeout": self._timeout,
                    "generation": self._generation}

    def _hosts_locked(self, g: _BrokerGroup, members: list) -> Dict[str, Optional[str]]:
        """Machine identity (boot id) per member, as pinged in.  Pushed with
        every membership epoch so all members share ONE consistent view —
        the tree-vs-ring auto-selection (``Group.ring_auto``) is part of the
        wire protocol and must be decided identically cohort-wide."""
        return {name: (g.members[name].get("host") if name in g.members else None)
                for name in members}

    def _bump_locked(self, g: _BrokerGroup, now: float) -> list:
        """Advance the group's epoch and snapshot the member/host views.
        Returns the push list to issue OUTSIDE the lock."""
        g.needs_update = False
        g.last_update = now
        g.sync_id += 1
        g.active_members = sorted(
            g.members, key=lambda n: (g.members[n]["sort_order"], n)
        )
        utils.log_info(
            "broker: group %s sync_id=%d gen=%d members=%s",
            g.name,
            g.sync_id,
            self._generation,
            g.active_members,
        )
        members = list(g.active_members)
        g.active_hosts = self._hosts_locked(g, members)
        hosts = dict(g.active_hosts)
        return [(name, g.name, g.sync_id, members, hosts, self._generation)
                for name in members]

    def _on_leave(self, group_name: str, peer_name: str):
        """Graceful decommission: the peer announces its departure instead of
        going silent, so the cohort doesn't burn the ping-eviction timeout.
        The epoch bumps and pushes IMMEDIATELY — bypassing both the update()
        cadence and the churn rate limit — because a decommission is a planned,
        already-drained event: remaining members should re-form now."""
        with self._lock:
            if not self._primary:
                # A standby can't mint the epoch; the leaver falls back to
                # ping-silence eviction on whichever broker is primary.
                return {"left": False, "standby": True, "generation": self._generation}
            g = self._groups.get(group_name)
            if g is None:
                return {"left": False}
            if peer_name in g.observers:
                # Observer decommission: no epoch to bump, just deregister
                # (so ``__broker_list`` stops advertising it immediately —
                # the client-visible analogue of the member fast path).
                del g.observers[peer_name]
                return {"left": True, "sync_id": g.sync_id}
            if peer_name not in g.members:
                return {"left": False}
            del g.members[peer_name]
            pushes = self._bump_locked(g, time.monotonic())
            sync_id = g.sync_id
        for push in pushes:
            self._push_to(*push)
        return {"left": True, "sync_id": sync_id}

    def _on_list(self, group_name: str):
        """Discovery for non-members (``serving.ServeClient``): the live
        contributing roster (last epoch snapshot) plus the live observers
        with their roles.  Observers are a LIVE view — they have no epoch,
        and a client failing over wants the freshest liveness the broker
        has, not a rate-limited snapshot.  Standbys serve this too, from
        replicated state: discovery stays available while a failover is
        still electing the next primary."""
        with self._lock:
            g = self._groups.get(group_name)
            if g is None:
                return {"sync_id": None, "members": [], "observers": {},
                        "generation": self._generation,
                        "standby": not self._primary}
            return {
                "sync_id": g.sync_id,
                "members": list(g.active_members),
                "observers": {n: m["role"] for n, m in g.observers.items()},
                "generation": self._generation,
                "standby": not self._primary,
            }

    def _on_resync(self, group_name: str, peer_name: str):
        """A client whose sync_id went stale asks for the member list again."""
        with self._lock:
            if not self._primary:
                return {"sync_id": None, "standby": True,
                        "generation": self._generation}
            g = self._groups.get(group_name)
            if g is None:
                return None
            push = (g.name, g.sync_id, list(g.active_members),
                    dict(g.active_hosts), self._generation)
        self._push_to(peer_name, *push)
        return {"sync_id": push[1], "generation": push[4]}

    def _on_status(self):
        """Read-only probe for failover scans: who am I, what generation,
        am I serving.  Never mutates state (unlike a ping, this must not
        promote a standby — a scan is a question, not a vote)."""
        with self._lock:
            return {
                "name": self._rpc.get_name(),
                "generation": self._generation,
                "primary": self._primary,
                "groups": {name: g.sync_id for name, g in self._groups.items()},
                "timeout": self._timeout,
            }

    # replication -------------------------------------------------------------
    def _snapshot_locked(self) -> dict:
        return {
            g.name: {
                "sync_id": g.sync_id,
                "members": {
                    n: {"sort_order": m["sort_order"], "host": m.get("host")}
                    for n, m in g.members.items()
                },
                "observers": {n: {"role": m["role"]} for n, m in g.observers.items()},
                "active_members": list(g.active_members),
                "active_hosts": dict(g.active_hosts),
            }
            for g in self._groups.values()
        }

    def _on_replicate(self, from_name: str, generation: int, state: dict):
        """A primary's state snapshot.  Accept iff the sender wins the
        generation fence against us ((generation, name) — higher generation
        wins, name breaks ties); otherwise reject so the STALE sender
        demotes.  This exchange is the post-partition-heal convergence
        mechanism in both directions: whichever of two transient primaries
        loses the fence becomes the other's standby."""
        now = time.monotonic()
        with self._lock:
            generation = int(generation)
            if self._primary:
                # Primary-vs-primary: the (generation, name) fence picks ONE
                # survivor.  Name only breaks exact generation ties — between
                # a primary and its own standbys generations differ or the
                # standby accepts below.
                mine = (self._generation, self._rpc.get_name())
                theirs = (generation, str(from_name))
                if theirs <= mine:
                    _M_REPL_REJECTS.inc()
                    return {"ok": False, "generation": self._generation,
                            "name": self._rpc.get_name()}
                self._demote_locked(generation, f"replication from {from_name}")
            else:
                if generation < self._generation:
                    # Stale zombie replicating at us: refuse, and tell it the
                    # real generation so it stands down.
                    _M_REPL_REJECTS.inc()
                    return {"ok": False, "generation": self._generation,
                            "name": self._rpc.get_name()}
                self._generation = generation
                _M_GENERATION.set(self._generation)
            self._last_replicate_rx = now
            groups: Dict[str, _BrokerGroup] = {}
            for name, snap in state.items():
                g = _BrokerGroup(name)
                g.sync_id = int(snap["sync_id"])
                # Liveness re-stamped at apply time: if we're promoted later,
                # every replicated member gets a full ping window before the
                # eviction sweep may touch it.
                g.members = {
                    n: {"last_ping": now, "sort_order": m["sort_order"],
                        "host": m.get("host")}
                    for n, m in snap["members"].items()
                }
                g.observers = {
                    n: {"last_ping": now, "role": m["role"]}
                    for n, m in snap["observers"].items()
                }
                g.active_members = list(snap["active_members"])
                g.active_hosts = dict(snap["active_hosts"])
                groups[name] = g
            self._groups = groups
            _M_REPL_APPLIED.inc()
            return {"ok": True, "generation": self._generation,
                    "name": self._rpc.get_name()}

    def _replicate_locked(self) -> list:
        """Build the replication sends to issue OUTSIDE the lock."""
        snapshot = self._snapshot_locked()
        sends = []
        own = self._rpc.get_name()
        for addr in self._peer_broker_addrs:
            name = self._rpc.peer_name_at(addr)
            if name is None or name == own:
                continue  # not greeted yet (down or still dialing) — skip
            sends.append((name, self._generation, snapshot))
        return sends

    def _send_replicate(self, peer_name: str, generation: int, snapshot: dict) -> None:
        def _reply(result, error):
            if error is not None:
                utils.log_verbose("broker: replicate to %s failed: %s",
                                  peer_name, error)
                return
            if isinstance(result, dict) and not result.get("ok", True):
                r_fence = (int(result.get("generation", 0)),
                           str(result.get("name", "")))
                with self._lock:
                    if self._primary and r_fence > (self._generation,
                                                    self._rpc.get_name()):
                        self._demote_locked(r_fence[0],
                                            f"replication rejected by {r_fence[1]}")

        self._rpc.async_callback(
            peer_name, "__broker_replicate", _reply,
            self._rpc.get_name(), generation, snapshot,
        )

    # pump --------------------------------------------------------------------
    def update(self) -> None:
        """Evict silent peers and push membership epochs. Call regularly
        (~0.25 s cadence, reference ``py/moolib/broker.py:31-36``)."""
        now = time.monotonic()
        pushes = []
        replicates = []
        with self._lock:
            if self._primary:
                for g in self._groups.values():
                    evicted = [
                        name
                        for name, m in g.members.items()
                        if now - m["last_ping"] > self._timeout
                    ]
                    for name in evicted:
                        del g.members[name]
                        g.needs_update = True
                    # Observer eviction never bumps the epoch: replicas dying
                    # must not cancel the training cohort's in-flight rounds.
                    for name in [
                        n for n, m in g.observers.items()
                        if now - m["last_ping"] > self._timeout
                    ]:
                        del g.observers[name]
                    # Rate-limit epoch bumps (reference: 2 s; we use 0.5 s so
                    # tests with churn settle fast).
                    if g.needs_update and now - g.last_update > 0.5:
                        pushes.extend(self._bump_locked(g, now))
                if (self._peer_broker_addrs
                        and now - self._last_replicate_tx >= self._replicate_interval):
                    self._last_replicate_tx = now
                    replicates = self._replicate_locked()
            # A standby neither evicts (its liveness stamps are replication
            # apply times, not real pings) nor pushes epochs — it only keeps
            # the promotion clock, which _on_ping reads.
        for push in pushes:
            self._push_to(*push)
        for send in replicates:
            self._send_replicate(*send)

    def _push_to(self, peer_name: str, group_name: str, sync_id: int, members: list,
                 hosts: Optional[dict] = None, generation: Optional[int] = None) -> None:
        def _ignore(result, error):
            if error is not None:
                utils.log_verbose("broker: push to %s failed: %s", peer_name, error)

        self._rpc.async_callback(
            peer_name, "__group_update", _ignore, group_name, sync_id, members,
            hosts, generation,
        )

    def close(self) -> None:
        self._rpc.close()


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description="moolib_tpu broker")
    parser.add_argument("--address", default="0.0.0.0:4431")
    parser.add_argument("--name", default="broker")
    parser.add_argument("--interval", type=float, default=0.25)
    parser.add_argument(
        "--brokers", default=None,
        help="comma-separated addresses of the OTHER brokers in this control "
             "plane (enables replication + failover)")
    parser.add_argument(
        "--standby", action="store_true",
        help="start as a hot standby (promotes itself when the primary's "
             "replication goes quiet past --promote_grace)")
    parser.add_argument("--promote_grace", type=float, default=3.0)
    parser.add_argument("--replicate_interval", type=float, default=0.5)
    parser.add_argument("--timeout", type=float, default=10.0,
                        help="ping-silence eviction timeout (seconds)")
    args = parser.parse_args(argv)

    rpc = Rpc()
    broker = Broker(rpc, standby=args.standby)
    broker.set_name(args.name)
    broker.set_timeout(args.timeout)
    broker.set_promote_grace(args.promote_grace)
    broker.set_replicate_interval(args.replicate_interval)
    broker.listen(args.address)
    if args.brokers:
        broker.set_peer_brokers([a.strip() for a in args.brokers.split(",") if a.strip()])
    role = "standby" if args.standby else "primary"
    print(f"Broker {args.name!r} ({role}) listening on {args.address}", flush=True)
    try:
        while True:
            broker.update()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        broker.close()


if __name__ == "__main__":
    main()
