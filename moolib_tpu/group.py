"""Elastic peer groups and binary-tree allreduce over RPC.

Counterpart of the reference's ``GroupService``/``AllReduceService``/``Group``
(``src/group.{h,cc}``): clients ping the broker, receive membership epochs
(``sync_id``), and run allreduce over a binary tree laid out by member index —
leaf→root reduction, then the result is shared back down the same tree.
Out-of-order contributions (a peer that learned the new epoch before us) are
parked and consumed when the local operation starts (reference retry queue,
``src/group.h:662-679``).  A membership change cancels every in-flight
reduction with a "group changed" error — elasticity comes from the epoch key,
not from any attempt to patch a running reduction.

TPU note: this RPC tree is the *control/elastic* data plane (DCN-class).  For
a static cohort that forms a jax device mesh, gradient reduction should ride
XLA collectives over ICI instead — see ``moolib_tpu.parallel`` and the
Accumulator's mesh backend.
"""

from __future__ import annotations

import copy
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import buckets, telemetry, utils
from .telemetry.recovery import observe_phase
from .utils import nest
from .rpc import Future, Rpc, RpcError
from .rpc.core import adopt_current_frame

_REG = telemetry.get_registry()
_M_FAILOVERS = _REG.counter(
    "group_broker_failovers_total",
    "Broker failover scans this peer started (ping silence or standby reply)",
)
_M_STALE_PUSHES = _REG.counter(
    "group_stale_pushes_total",
    "Epoch pushes rejected by the peer-side generation fence (zombie ex-primary)",
)

_OPS: Dict[str, Callable] = {
    "sum": lambda a, b: a + b,
    "product": lambda a, b: a * b,
    "min": lambda a, b: np.minimum(a, b) if _is_arr(a) else min(a, b),
    "max": lambda a, b: np.maximum(a, b) if _is_arr(a) else max(a, b),
}


def _is_arr(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _ring_threshold() -> int:
    """Payload size (bytes) above which ``all_reduce`` auto-selects the
    chunked ring path.  Read per call so tests can force it; MUST be set
    identically on every peer (path choice is part of the op's protocol)."""
    return int(os.environ.get("MOOLIB_RING_THRESHOLD", 1 << 20))


def _bucket_threshold() -> int:
    """Payload size (bytes) above which a tree ``all_reduce`` auto-selects
    the flat-bucket path (zero-copy serialization + in-place combine, one
    sub-op per bucket).  Like the ring threshold it is wire protocol: set it
    identically on every peer."""
    return int(os.environ.get("MOOLIB_BUCKET_THRESHOLD", 1 << 20))


def _own(value):
    """Deep-copy any array leaf that does not own writable memory.

    Inline RPC handlers (``__group_reduce``/``__group_ring``/``__group_share``)
    receive arrays as ZERO-COPY views over the transport's receive buffer,
    valid only for the duration of the call — anything parked, queued, or
    otherwise retained past the handler return must take ownership first.
    Copying non-owning leaves (rather than tracking provenance) also covers
    values the caller handed us as views; the copy is exactly the one the
    old copying deserializer used to make, so retention paths cost the same
    as before while the consume-immediately paths become zero-copy."""

    def f(x):
        if isinstance(x, np.ndarray):
            if not x.flags.owndata or not x.flags.writeable:
                return np.array(x)
            return x
        if x is None or isinstance(
            x, (bool, int, float, complex, str, bytes, np.generic)
        ):
            return x
        if hasattr(x, "copy_to_host_async"):
            return x  # device array: deserialization always copies jax leaves
        # Opaque leaf (custom-op payloads): nest.map can't see inside it, but
        # it may embed borrowed receive-buffer views — deepcopy owns them.
        return copy.deepcopy(x)

    return nest.map(f, value)


def _ring_codec(wire):
    """(encode, decode, acc_cast) for per-hop ring wire compression.

    ``encode`` maps an accumulation-dtype chunk to its wire form before every
    hop; ``decode`` maps a wire object back to the accumulation dtype;
    ``acc_cast`` lifts a local contribution into the accumulation dtype.
    With a wire dtype set, partial sums accumulate in float32 and are
    re-rounded once per hop — the same contract as the tree's ``finalize``
    (see ``accumulator._wire_finalize``).  ``wire="q8"`` is symmetric int8
    with one scale per chunk (the per-tensor scheme of the accumulator's
    q8 path, applied at chunk granularity).
    """
    if wire is None:
        ident = lambda a: a  # noqa: E731
        return ident, ident, ident
    if wire == "q8":

        def enc(a):
            a = np.asarray(a, np.float32)
            amax = float(np.max(np.abs(a))) if a.size else 0.0
            if amax == 0.0:
                return {"q8": np.zeros(a.shape, np.int8), "s": 0.0}
            scale = amax / 127.0
            return {"q8": np.round(a / scale).astype(np.int8), "s": scale}

        def dec(obj):
            return obj["q8"].astype(np.float32) * obj["s"]

        return enc, dec, lambda a: np.asarray(a, np.float32)
    wd = np.dtype(wire)
    return (
        lambda a: np.asarray(a).astype(wd),
        lambda a: np.asarray(a).astype(np.float32),
        lambda a: np.asarray(a, np.float32),
    )


def _ring_nbytes(value) -> int:
    """Payload bytes if ring-eligible (all-array pytree, one dtype), else -1."""
    leaves = list(nest.flatten(value))
    if not leaves or not all(_is_arr(l) for l in leaves):
        return -1
    dtypes = {np.dtype(l.dtype) for l in leaves}
    if len(dtypes) != 1:
        return -1
    itemsize = dtypes.pop().itemsize
    return sum(int(l.size) for l in leaves) * itemsize


def _payload_nbytes(value) -> int:
    """Rough array/bytes payload size of a share result — cheap gate for
    the memfd-multicast star (small results must stay on tree forwarding:
    below the memfd threshold the star degrades to O(n) root unicasts)."""
    total = 0
    for leaf in nest.flatten(value):
        if isinstance(leaf, np.ndarray):
            total += leaf.nbytes
        elif isinstance(leaf, (bytes, bytearray, memoryview)):
            total += len(leaf)
    return total


def _memfd_min() -> int:
    from .rpc.core import _MEMFD_MIN

    return _MEMFD_MIN


def _resolve_op(op) -> Callable:
    """Builtin string ops reduce leaf-wise over pytrees; a user callable is
    applied to the *whole* contributed values (so lexicographic tuple compares
    and struct-valued reductions like the Accumulator's work — reference
    ``ReduceVariant`` custom py::object ops, ``src/group.h:230-262``)."""
    if isinstance(op, str):
        leaf_op = _OPS[op]
        return lambda a, b: nest.map_many(leaf_op, a, b)
    return op


class AllReduce(Future):
    """A future result of an AllReduce operation (same API as reference)."""


class _Completer:
    """One lazily-started daemon thread running bucketed-round completions.

    Completion must leave the transport IO thread (inline handlers run
    there; user done-callbacks are arbitrary code) but must not queue
    behind the Rpc executor's handler dispatch either — a round's
    completion gates the caller's next round, and executor queueing under
    load costs milliseconds per op on that critical path.
    """

    def __init__(self):
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def __call__(self, fn, *args) -> None:
        if self._thread is None:
            with self._lock:
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="moolib-group-complete",
                        daemon=True)
                    self._thread.start()
        self._q.put((fn, args))

    def _run(self) -> None:
        while True:
            fn, args = self._q.get()
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - callback bugs must not kill the thread
                utils.log_error(
                    "allreduce completion callback failed:\n%s",
                    traceback.format_exc())


class _Op:
    __slots__ = (
        "key", "value", "op", "finalize", "future", "contribs", "sent_up",
        "started_at", "eager", "folded", "consume",
    )

    def __init__(self, key, value, op, finalize, future, eager=False, consume=None):
        self.key = key
        self.value = value
        self.op = op
        self.finalize = finalize
        self.future = future
        self.contribs: List[Any] = []
        self.sent_up = False
        self.started_at = time.monotonic()
        # Eager ops (commutative + associative, e.g. the flat-bucket sum)
        # fold each child contribution the moment it arrives — while the
        # borrowed receive buffer is still valid — instead of parking it in
        # ``contribs``.  That is what turns materialize-then-copy into one
        # in-place ``np.add(acc, view, out=acc)`` pass.
        self.eager = eager
        self.folded = 0
        # Optional share-path hook: consume(result) takes the (borrowed)
        # shared-down result and returns an OWNED value (the bucketed path
        # copies straight into its preallocated result buffer).  None means
        # the generic _own() deep copy.
        self.consume = consume


class _RingOp:
    """State of one chunked ring allreduce (reduce-scatter + all-gather).

    Bandwidth-optimal counterpart of the reference's benchmark-only chunked
    ring (``test/test_multinode_allreduce.cc:16-150``), made a first-class
    epoch-keyed Group op: each of the N members sends ``2*(N-1)/N`` of the
    payload instead of the tree's full payload per hop (and the tree root's
    ``2x`` full payloads), so serialization cost is spread evenly across the
    cohort and chunks pipeline across ring steps.

    Protocol (rank r, ring next = (r+1) % n, chunks split near-equally):
      - reduce-scatter step s in [0, n-2]: send chunk ``(r - s) % n``
        (local contribution at s=0, accumulated partial after), receive
        chunk ``(r - 1 - s) % n`` and fold in the local contribution.
        After the last step, rank r owns the fully reduced chunk
        ``(r + 1) % n`` plus the fully combined ``meta``.
      - all-gather step s in [0, n-2]: send the completed chunk
        ``(r + 1 - s) % n``; receive ``(r - s) % n`` and forward its wire
        bytes unchanged (every rank decodes identical bytes, so wire
        compression stays bit-consistent cohort-wide).

    ``local[c] is None`` marks a zero (skip) contribution: markers forward
    without materializing zero payloads, so an all-skip round costs ~nothing
    on the wire (sum only).  Out-of-order frames park in ``pending`` keyed by
    (phase, step); steps are processed strictly in order per phase.
    """

    __slots__ = (
        "key", "future", "started_at", "members", "rank", "n", "local",
        "chunk_sizes", "dtype", "template", "leaf_shapes", "has_value",
        "enc", "dec", "acc_cast", "leaf_op", "op_name", "meta", "has_meta", "meta_op",
        "meta_total", "rs_next", "ag_next", "pending", "final", "done_chunks",
        "pumping", "repump", "sent_initial",
    )

    def __init__(self, key, value, op_name, future, members, rank, wire,
                 meta, meta_op, template, chunk_align=None):
        self.key = key
        self.future = future
        self.started_at = time.monotonic()
        self.members = members
        self.rank = rank
        self.n = len(members)
        self.enc, self.dec, self.acc_cast = _ring_codec(wire)
        self.leaf_op = _OPS[op_name]
        self.op_name = op_name
        self.meta = meta
        self.has_meta = meta is not None
        self.meta_op = meta_op
        self.meta_total = None
        self.rs_next = 0
        self.ag_next = 0
        self.pending: Dict[Tuple[str, int], Tuple] = {}
        self.final: List[Any] = [None] * self.n
        self.done_chunks = 0
        self.pumping = False
        self.repump = False
        self.sent_initial = False

        self.has_value = value is not None
        shape_src = value if value is not None else template
        if shape_src is None:
            raise RpcError("ring allreduce with value=None requires template=")
        leaves = [np.asarray(l) for l in nest.flatten(shape_src)]
        if not leaves:
            raise RpcError("ring allreduce needs at least one array leaf")
        dtypes = {l.dtype for l in leaves}
        if len(dtypes) != 1:
            raise RpcError(f"ring allreduce needs one uniform dtype, got {dtypes}")
        self.dtype = leaves[0].dtype
        self.template = shape_src
        self.leaf_shapes = [l.shape for l in leaves]
        total = sum(l.size for l in leaves)
        if chunk_align and int(chunk_align) > 0 and total > 0:
            # Bucket-aligned chunking: boundaries fall on multiples of
            # ``chunk_align`` elements (the accumulator passes its flat
            # bucket size), so ring chunks coincide with bucket slices of
            # the flat payload — contiguous zero-copy views end to end.
            # Same value required on every peer (boundaries are protocol).
            # Clamp to the even split's granularity for small payloads
            # (total < n aligned units): full-size alignment would leave
            # peers with empty chunks and pile the work on the rest.  The
            # clamp is a pure function of (total, n, align) so every peer
            # still computes identical boundaries.
            align = min(int(chunk_align), -(-total // self.n))
            units = -(-total // align)
            bu, rem_u = divmod(units, self.n)
            sizes, off = [], 0
            for c in range(self.n):
                u = bu + (1 if c < rem_u else 0)
                sz = min(u * align, total - off)
                sizes.append(sz)
                off += sz
            self.chunk_sizes = sizes
        else:
            base, rem = divmod(total, self.n)
            self.chunk_sizes = [base + (1 if c < rem else 0) for c in range(self.n)]
        if value is not None:
            flat = np.concatenate([l.ravel() for l in leaves]) if len(leaves) > 1 \
                else leaves[0].ravel()
            self.local = []
            off = 0
            for sz in self.chunk_sizes:
                self.local.append(self.acc_cast(flat[off:off + sz]))
                off += sz
        else:
            self.local = [None] * self.n

    # -- pure state transitions (call under the group lock) -----------------
    def drain(self):
        """Process every ready pending frame; return deferred actions
        (sends / completion) for the caller to perform outside the lock."""
        actions: List[Tuple] = []
        if not self.sent_initial:
            self.sent_initial = True
            c = self.rank
            data = None if self.local[c] is None else self.enc(self.local[c])
            actions.append(("send", "rs", 0, c, data, self.meta))
        progressed = True
        while progressed:
            progressed = False
            if self.rs_next <= self.n - 2 and ("rs", self.rs_next) in self.pending:
                actions.extend(self._rs_step(*self.pending.pop(("rs", self.rs_next))))
                progressed = True
            if self.ag_next <= self.n - 2 and ("ag", self.ag_next) in self.pending:
                actions.extend(self._ag_step(*self.pending.pop(("ag", self.ag_next))))
                progressed = True
        if self.done_chunks == self.n:
            actions.append(("done",))
        return actions

    def _combine(self, incoming, c):
        mine = self.local[c]
        if incoming is None:
            return mine
        if mine is None:
            return incoming
        if (
            self.op_name == "sum"
            and isinstance(incoming, np.ndarray)
            and incoming.flags.writeable
            and incoming.dtype == np.asarray(mine).dtype
        ):
            # The decoded chunk is ours alone — accumulate in place instead
            # of allocating a fresh array every hop.
            np.add(incoming, mine, out=incoming)
            return incoming
        return self.leaf_op(incoming, mine)

    def _rs_step(self, chunk_idx, data, meta_in):
        s = self.rs_next
        self.rs_next += 1
        c = (self.rank - 1 - s) % self.n
        if chunk_idx != c:
            raise RpcError(
                f"ring protocol error: got chunk {chunk_idx} at rs step {s}, "
                f"expected {c} (peers disagree on membership?)")
        incoming = None if data is None else self.dec(data)
        if incoming is not None and incoming.size != self.chunk_sizes[c]:
            raise RpcError(
                f"ring chunk size mismatch ({incoming.size} != "
                f"{self.chunk_sizes[c]}): peers contributed different shapes")
        combined = self._combine(incoming, c)
        meta_acc = meta_in
        if self.has_meta:
            meta_acc = self.meta_op(meta_in, self.meta)
        if s == self.n - 2:
            # Chunk c is fully reduced; this rank owns it. Round-trip the
            # wire encoding so every rank decodes identical bytes.
            encoded = None if combined is None else self.enc(combined)
            self.final[c] = None if encoded is None else self.dec(encoded)
            self.meta_total = meta_acc
            self.done_chunks += 1
            return [("send", "ag", 0, c, encoded, meta_acc)]
        encoded = None if combined is None else self.enc(combined)
        return [("send", "rs", s + 1, c, encoded, meta_acc)]

    def _ag_step(self, chunk_idx, data, meta_total):
        s = self.ag_next
        self.ag_next += 1
        c = (self.rank - s) % self.n
        if chunk_idx != c:
            raise RpcError(
                f"ring protocol error: got chunk {chunk_idx} at ag step {s}, "
                f"expected {c}")
        self.final[c] = None if data is None else self.dec(data)
        if self.meta_total is None:
            self.meta_total = meta_total
        self.done_chunks += 1
        if s < self.n - 2:
            return [("send", "ag", s + 1, c, data, meta_total)]
        return []

    def assemble(self):
        """Reassemble the reduced pytree from final chunks (outside lock)."""
        if all(f is None for f in self.final):
            value = None
        else:
            parts = []
            for c, f in enumerate(self.final):
                if f is None:
                    parts.append(np.zeros(self.chunk_sizes[c], self.dtype))
                else:
                    parts.append(np.asarray(f).astype(self.dtype, copy=False))
            flat = np.concatenate(parts) if len(parts) > 1 else parts[0]
            leaves, off = [], 0
            for shape in self.leaf_shapes:
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                leaves.append(flat[off:off + size].reshape(shape))
                off += size
            value = nest.pack_as(self.template, leaves)
        if self.has_meta:
            return value, self.meta_total
        return value


class _BucketedReduce:
    """Parent state of one flat-bucket tree allreduce.

    The payload is flattened once into fixed-size contiguous buckets
    (``buckets.BucketLayout``); each bucket rides the binary tree as its own
    EAGER sub-op, so buckets pipeline independently through the engine
    (serialization of bucket k overlaps the wire/combine of bucket k-1) and
    every hop folds contributions **in place** off the borrowed receive
    buffer (``np.add(acc, view, out=acc)``) instead of materialize-then-copy.
    Buffers come from the refcount-guarded pool in ``moolib_tpu.buckets``:

    - ``stage_flat``: the local contribution, staged once (multi-leaf
      payloads, or single-leaf ones handed over with ``owned=True``); folds
      accumulate directly into it.
    - ``acc_flat``: lazily leased when the local contribution is a borrowed
      user array (or a skip) — the first fold fuses the legacy materialize
      copy with the first add.
    - ``result_flat``: lazily leased on the share-down path; the consume
      hook copies each bucket result straight off the receive buffer into
      its slice (one pass, no intermediate array).

    Wire compression reuses the ring's per-chunk codec (``_ring_codec``):
    contributions and partial sums travel encoded per hop, accumulate in
    f32, and the root's final encode is what every peer decodes —
    bit-consistent cohort-wide, same contract as the tree's old finalize.
    """

    __slots__ = (
        "template", "layout", "acc_dtype", "wire", "enc", "dec", "meta_op",
        "has_meta", "owned", "defer", "flat_view", "stage_flat", "stage_owned",
        "acc_flat", "result_flat", "results", "meta_total", "pending", "done",
        "future", "key", "started_at", "cleanup", "_lock",
    )

    def __init__(self, value, meta, meta_op, wire, template, owned, defer):
        self.wire = wire
        self.meta_op = meta_op
        self.has_meta = meta is not None
        self.enc, self.dec, _ = _ring_codec(wire)
        shape_src = value if value is not None else template
        if shape_src is None:
            raise RpcError("bucketed allreduce with value=None requires template=")
        leaves = [np.asarray(l) for l in nest.flatten(shape_src)]
        if not leaves:
            raise RpcError("bucketed allreduce needs at least one array leaf")
        dtypes = {l.dtype for l in leaves}
        if len(dtypes) != 1:
            raise RpcError(f"bucketed allreduce needs one uniform dtype, got {dtypes}")
        dtype = leaves[0].dtype
        self.template = shape_src
        self.layout = buckets.BucketLayout([l.shape for l in leaves], dtype)
        self.acc_dtype = np.dtype(np.float32) if wire is not None else dtype
        self.owned = owned
        self.defer = defer  # run fn(*args) off the transport IO thread
        self.stage_flat = None
        self.stage_owned = False  # True: recycle stage_flat at completion
        self.acc_flat = None
        self.result_flat = None
        self.flat_view = None
        if value is not None:
            if len(leaves) == 1 and leaves[0].flags.c_contiguous and (
                owned or leaves[0].dtype == self.acc_dtype
            ):
                # Zero-copy staging: the contribution IS the caller's array.
                # owned=True additionally lets folds accumulate into it.
                lf = leaves[0]
                self.flat_view = lf if lf.ndim == 1 else lf.reshape(-1)
                if owned and lf.dtype == self.acc_dtype:
                    self.stage_flat = self.flat_view
                    self.stage_owned = True
            else:
                self.stage_flat = buckets.lease(self.layout.total, self.acc_dtype)
                self.layout.fill(self.stage_flat, leaves)
                self.flat_view = self.stage_flat
                self.stage_owned = True
        n = self.layout.n_buckets
        self.results: List[Any] = [None] * n
        self.meta_total = None
        self.pending = n
        self.done = False
        self.future: Optional[AllReduce] = None
        # Registered in Group._ops under the PARENT key as a mismatch
        # sentinel (a legacy tree frame arriving there means the cohort
        # disagrees on the path) — key/started_at let the timeout sweep
        # treat it like any other op.
        self.key = None
        self.started_at = time.monotonic()
        # Set by the group: deregisters the sentinel when the round ends
        # from within (the timeout sweep / epoch change remove it
        # themselves).  Not a future done-callback on purpose — those mark
        # the future as having user callbacks, which would force every
        # completion through the completer-thread hop.
        self.cleanup: Optional[Callable] = None
        self._lock = threading.Lock()

    def attach(self, future: AllReduce) -> None:
        self.future = future

    def _complete(self, fn, *args) -> None:
        """Run a completion step: deferred to the completer thread when the
        round future has user done-callbacks (arbitrary code must not run
        on the transport IO thread), inline otherwise — completing a
        callback-less future is just an event-set, and a thread hop costs
        a full scheduler quantum on small boxes.  A callback registered in
        the instant between the check and the set still runs safely: a
        done future runs it on the adder's own thread."""
        if self.future is not None and self.future._callbacks:
            self.defer(fn, *args)
        else:
            fn(*args)

    # -- per-bucket hooks (run under the GROUP lock via _Op machinery) ------
    def _acc_slice(self, k):
        s, e = self.layout.bounds[k]
        if self.stage_flat is not None:
            return self.stage_flat[s:e]
        if self.acc_flat is None:
            self.acc_flat = buckets.lease(self.layout.total, self.acc_dtype)
        return self.acc_flat[s:e]

    def _result_slice(self, k):
        s, e = self.layout.bounds[k]
        if self.result_flat is None:
            self.result_flat = buckets.lease(self.layout.total, self.acc_dtype)
        return self.result_flat[s:e]

    def _decode_into(self, dst, b):
        """dst[:] = decode(b) in ONE pass (no intermediate array for the
        common uncompressed and q8 cases)."""
        if self.wire is None:
            np.copyto(dst, b)
        elif self.wire == "q8":
            np.multiply(b["q8"], np.float32(b["s"]), out=dst)
        else:
            np.copyto(dst, b, casting="unsafe")

    def _add_into(self, dst, b):
        """dst += decode(b) in place."""
        if self.wire is None:
            np.add(dst, b, out=dst)
        elif self.wire == "q8":
            np.add(dst, b["q8"] * np.float32(b["s"]), out=dst)
        else:
            np.add(dst, np.asarray(b, np.float32), out=dst)

    def _fold(self, k, total, c):
        m = c.get("m")
        if m is not None:
            total["m"] = m if total.get("m") is None else self.meta_op(total["m"], m)
        b = c.get("b")
        if b is None:
            return total
        tb = total.get("b")
        acc = self._acc_slice(k)
        if tb is None:
            # Local skip: own the first incoming straight into the bucket.
            self._decode_into(acc, b)
            total["b"] = acc
        elif tb is acc or np.may_share_memory(tb, acc):
            self._add_into(tb, b)
        else:
            # First fold over a borrowed local view: fuse the copy the
            # legacy receive path used to make with the first add.
            if tb.dtype != self.acc_dtype:
                tb = np.asarray(tb, self.acc_dtype)
            if self.wire is None:
                np.add(tb, b, out=acc)
            elif self.wire == "q8":
                np.add(tb, b["q8"] * np.float32(b["s"]), out=acc)
            else:
                np.add(tb, np.asarray(b, np.float32), out=acc)
            total["b"] = acc
        return total

    def _fin(self, p):
        """Per-hop wire encode of a bucket payload (identity without wire)."""
        b = p.get("b")
        if b is None or self.wire is None:
            return p
        if isinstance(b, dict):
            return p  # already encoded (defensive; folds keep acc form)
        out = dict(p)
        out["b"] = self.enc(b)
        return out

    def _consume(self, k, val):
        """Share-path hook: copy the borrowed result straight into the
        preallocated result slice (one pass off the receive buffer).

        Returns ``(owned, forward)``: the owned decoded value this peer
        keeps, and the payload to forward down the tree.  Uncompressed they
        are the same object (slice views — the forward serializes
        zero-copy); under wire compression the forward keeps the ENCODED
        bytes (owned copy) so every peer in the subtree decodes identical
        bytes — the tree-wide bit-consistency contract."""
        b = val.get("b")
        m = val.get("m")
        if b is None:
            out = {"b": None, "m": m}
            return out, out
        if (
            self.owned
            and self.wire is None
            and self.layout.n_buckets == 1
            and self.result_flat is None
            and isinstance(b, np.ndarray)
            and b.size == self.layout.total
        ):
            # Zero-copy terminus: adopt the memfd mapping the share arrived
            # in — the result stays in the shared pages (read-only) instead
            # of being copied out.  Single-bucket only: multi-bucket results
            # must land contiguously in one flat.  Gated on owned=True: a
            # read-only result view is part of that engine-style contract,
            # while plain all_reduce callers keep writable results.
            adopted = adopt_current_frame()
            if adopted is not None:
                base = adopted.__array_interface__["data"][0]
                off = b.__array_interface__["data"][0] - base
                if 0 <= off and off + b.nbytes <= adopted.nbytes:
                    view = adopted[off:off + b.nbytes].view(self.acc_dtype)
                    self.result_flat = view
                    out = {"b": view, "m": m}
                    return out, out
        dst = self._result_slice(k)
        self._decode_into(dst, b)
        out = {"b": dst, "m": m}
        if self.wire is None:
            return out, out
        return out, {"b": _own(b), "m": m}

    # -- assembly ----------------------------------------------------------
    def _settled(self, b) -> bool:
        """Is this bucket result already sitting in one of our flats?"""
        if not isinstance(b, np.ndarray):
            return False
        for f in (self.stage_flat, self.acc_flat, self.result_flat):
            if f is not None and np.may_share_memory(b, f):
                return True
        return False

    def _child_done(self, k, fut):
        err = fut.exception()
        with self._lock:
            if self.done:
                return
            if err is None:
                r = fut.result(0)
                b = r.get("b")
                if b is not None and not self._settled(b):
                    # Root result under wire compression arrives encoded
                    # (the finalized form every peer decodes) — decode into
                    # the result buffer for bit-consistency with the cohort.
                    dst = self._result_slice(k)
                    self._decode_into(dst, b)
                    b = dst
                self.results[k] = (True, b)
                if k == 0:
                    self.meta_total = r.get("m")
                self.pending -= 1
                if self.pending > 0:
                    return
            self.done = True
        if err is not None:
            self._recycle()
            self._complete(self.future.set_exception, err)
            return

        def _finish():
            try:
                result = self._assemble()
            except Exception as e:  # noqa: BLE001 - surface assembly bugs
                self._recycle()
                self.future.set_exception(e)
                return
            # Recycle BEFORE completing: the caller's next round starts the
            # moment the future resolves, and its leases should find this
            # round's flats already back in the pool (the result views keep
            # their buffer alive; aliased freelist entries are skipped).
            self._recycle()
            self.future.set_result(result)

        # Assembly + user done-callbacks run on the completer thread, never
        # on the transport IO thread the inline handlers execute on (inline
        # only for callback-less futures, where completion is an event-set).
        self._complete(_finish)

    def _fail(self, err) -> None:
        """Error the whole bucketed round (protocol mismatch detection);
        idempotent against racing child completions."""
        with self._lock:
            if self.done:
                return
            self.done = True
        self._recycle()
        self._complete(self.future.set_exception, err)

    def _recycle(self):
        """Offer the round's flats back to the pool.  Eager by design:
        entries still aliased (pinned sends, the result views just handed to
        the caller) sit in the freelist untouched until their references die
        — lease()'s refcount probe never hands out aliased memory.  Runs on
        every from-within terminal path, so it also deregisters the group's
        mismatch sentinel."""
        if self.cleanup is not None:
            self.cleanup()
            self.cleanup = None  # the closure references us: break the cycle
        if self.stage_owned:
            buckets.release(self.stage_flat)
        buckets.release(self.acc_flat)
        buckets.release(self.result_flat)
        # Drop our own references immediately: anything still keeping this
        # object alive (a stray closure, a parked error path) would
        # otherwise pin every flat at refcount > pool-only and defeat
        # lease()'s reuse probe for the rest of the process.
        self.stage_flat = self.acc_flat = self.result_flat = None
        self.flat_view = None
        self.results = []

    def _assemble(self):
        vals = [b for (_, b) in self.results]
        if all(b is None for b in vals):
            return (None, self.meta_total) if self.has_meta else None
        flat = self.result_flat
        if flat is None:
            flat = self.acc_flat if self.acc_flat is not None else self.stage_flat
        for k, b in enumerate(vals):
            s, e = self.layout.bounds[k]
            dst = flat[s:e]
            if b is None:
                dst[:] = 0
            elif not np.may_share_memory(b, dst):
                np.copyto(dst, b)
        leaves = self.layout.unflatten(flat)
        if self.acc_dtype != self.layout.dtype:
            leaves = [l.astype(self.layout.dtype, copy=False) for l in leaves]
        value = nest.pack_as(self.template, leaves)
        return (value, self.meta_total) if self.has_meta else value


class Group:
    """A group of Rpc peers allowing coordinated AllReduce (reference API:
    update/set_broker_name/set_timeout/set_sort_order/members/sync_id/name/
    active/all_reduce)."""

    def __init__(self, rpc: Rpc, name: str):
        self._rpc = rpc
        self._name = name
        self._broker_name = "broker"
        self._timeout = 60.0
        self._sort_order = 0
        self._role = "member"
        self._lock = threading.RLock()
        self._sync_id: Optional[int] = None
        self._members: List[str] = []
        self._last_ping = 0.0
        self._ping_interval = 1.0
        self._ping_inflight = False
        # Ping cycle counter: bumped whenever an in-flight ping is abandoned
        # (overdue, or the failover scan retargeted the broker) so the late
        # reply from a dead/demoted broker can't clobber newer state.
        self._ping_seq = 0
        self._ping_fail_since: Optional[float] = None
        self._left = False
        self._stale_since: Optional[float] = None
        # --- broker high availability (multi-address control plane) ------
        # Addresses of every broker (primary + hot standbys).  Empty keeps
        # the legacy single-name mode: ping whatever set_broker_name said.
        self._broker_addrs: List[str] = []
        self._broker_resolved = False  # _broker_name learned from an address
        self._broker_gen = 0  # highest generation fence seen (0 = unfenced)
        self._broker_fail_after = 5.0  # ping silence before a failover scan
        self._failover: Optional[dict] = None  # in-flight scan state
        self._ops: Dict[Tuple, Any] = {}  # key -> _Op | _RingOp
        self._parked: Dict[Tuple, List[Any]] = {}
        self._ring_parked: Dict[Tuple, List[Tuple]] = {}
        self._park_t: Dict[Tuple, float] = {}  # park time, swept in update()
        self._seq: Dict[Tuple, int] = {}  # (sync_id, op name) -> next seq
        self._recv_seq: Dict[Tuple, int] = {}
        self._on_change_callbacks: List[Callable] = []
        self._member_hosts: Dict[str, Optional[str]] = {}
        # Machine identity sent with every broker ping (tests override it to
        # simulate cross-host cohorts on one box).
        from .rpc.core import _boot_id

        self._host_key = _boot_id()
        # Per-group completer: one group's slow user done-callback must not
        # gate another group's (another Accumulator's) round completion.
        self._completer = _Completer()
        self._register_handlers()

    # ------------------------------------------------------------------ setup
    def _register_handlers(self):
        # Several Groups can share one Rpc; handlers are defined once and
        # dispatch on the group name (first argument).
        registry = getattr(self._rpc, "_moolib_groups", None)
        if registry is None:
            registry = {}
            self._rpc._moolib_groups = registry
            rpc = self._rpc

            def dispatch(method):
                def handler(group_name, *args):
                    g = registry.get(group_name)
                    if g is None:
                        return None
                    return method(g, *args)

                return handler

            rpc.define("__group_update", dispatch(Group._on_update))
            # The allreduce data-plane handlers run INLINE on the receiving
            # IO thread with zero-copy borrowed payload views (Rpc.define):
            # eager bucket ops fold contributions in place straight off the
            # receive buffer; anything retained (parked frames, non-eager
            # contribs, shared results) is copied via _own()/consume hooks.
            rpc.define("__group_reduce", dispatch(Group._on_reduce), inline=True)
            rpc.define("__group_share", dispatch(Group._on_share), inline=True)
            rpc.define("__group_ring", dispatch(Group._on_ring), inline=True)
        if self._name in registry:
            raise RpcError(f"group {self._name!r} already exists on this Rpc")
        registry[self._name] = self

    # ------------------------------------------------------------------- api
    def set_broker_name(self, name: str) -> None:
        self._broker_name = name

    def set_brokers(self, addresses: List[str]) -> None:
        """Give this group the full broker control plane: the ADDRESSES of
        the primary and every hot standby (docs/RESILIENCE.md "Broker
        failover").  The Rpc dials and keeps a connection to each; the
        greeting resolves each address to the broker's rpc NAME (calls
        route by name).  Pings go to the current primary; when they go
        silent past ``set_broker_fail_after`` — or the broker answers as a
        demoted standby — the group scans ``__broker_status`` across the
        list and re-targets the highest-generation broker, recorded as a
        ``recovery_seconds{phase="broker_failover"}`` span."""
        self._broker_addrs = [a for a in addresses if a]
        self._broker_resolved = False
        for a in self._broker_addrs:
            self._rpc.connect(a)

    def set_broker_fail_after(self, seconds: float) -> None:
        """Ping silence (seconds) on the current broker before the failover
        scan starts.  Also bounds how long one unanswered ping is trusted."""
        self._broker_fail_after = float(seconds)

    def set_timeout(self, seconds: float) -> None:
        self._timeout = float(seconds)

    def set_sort_order(self, order: int) -> None:
        self._sort_order = int(order)

    def set_role(self, role: str) -> None:
        """Join the broker cohort as a NON-CONTRIBUTING member (any role
        string other than ``"member"``, e.g. ``"replica"``): the broker
        tracks this peer's liveness and advertises it via ``__broker_list``
        (serving-plane discovery), but it never enters the membership epoch
        — its joins, leaves, and deaths cannot bump ``sync_id`` or cancel
        the contributing cohort's in-flight reductions.  Observers receive
        no epoch pushes; ``active()`` stays False and ``all_reduce`` is not
        available to them.  Set before the first ``update()``."""
        self._role = str(role)

    def role(self) -> str:
        return self._role

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def member_hosts(self) -> Dict[str, Optional[str]]:
        """Machine identity (boot id) per member, from the broker's epoch
        push — every member sees the same mapping for a given ``sync_id``.
        ``None`` for members whose ping predates the host field."""
        with self._lock:
            return dict(self._member_hosts)

    def ring_auto(self, nbytes: int) -> bool:
        """The environment-aware tree-vs-ring choice for a payload of
        ``nbytes`` (VERDICT r4 weak #3: payload size alone is not enough).
        Ring when ALL of:

        - payload >= ``MOOLIB_RING_THRESHOLD`` (1 MiB default): below it the
          tree's single hop beats the ring's 2(n-1) message latency;
        - cohort size >= 3: at n=2 both algorithms move exactly one payload
          per peer and the tree is simpler;
        - the cohort spans more than one machine: same-host frames ride
          memfd zero-copy where wire bytes are nearly free, and the tree
          wins wall-clock (BENCH_LOCAL round 4); the ring's even per-peer
          load only pays on real NIC/DCN links.

        Deterministic cohort-wide: every input (threshold env, member list,
        host map) comes from the same broker epoch push, so peers at the
        same ``sync_id`` always agree — the path choice is wire protocol.
        """
        if nbytes < _ring_threshold():
            return False
        with self._lock:
            members = list(self._members)
            hosts = dict(self._member_hosts)
        if len(members) < 3:
            return False
        # "noboot-" keys are _boot_id's per-process random fallback (boot id
        # unreadable): they would make a same-host cohort look multi-machine.
        # Treat them as unknown — same policy as members with no host at all:
        # missing info must not silently disable the DCN optimization, and
        # must not manufacture a multi-host signal either.
        known = [
            None if h is None or h.startswith("noboot-") else h
            for h in (hosts.get(m) for m in members)
        ]
        if known and all(h is not None for h in known) and len(set(known)) == 1:
            return False
        return True

    def sync_id(self):
        return self._sync_id

    def name(self) -> str:
        return self._name

    def active(self) -> bool:
        with self._lock:
            return self._sync_id is not None and self._rpc.get_name() in self._members

    def add_change_callback(self, cb: Callable) -> None:
        """Extension over the reference: observe membership epoch changes."""
        self._on_change_callbacks.append(cb)

    def left(self) -> bool:
        return self._left

    def leave(self, timeout: float = 5.0) -> bool:
        """Graceful decommission: announce departure to the broker instead of
        going silent and burning the cohort's ping-eviction timeout.  The
        broker bumps the membership epoch immediately, so the remaining
        members re-form in sub-second time.  After this the group stops
        pinging and stays inactive; returns True once the broker acked the
        leave (False on timeout/error — the cohort then falls back to the
        ordinary eviction path, which is still correct, just slow)."""
        with self._lock:
            if self._left:
                return True
            self._left = True
            # Our own in-flight ops can never complete: we stop receiving
            # epoch pushes, so nothing would ever cancel them (the remaining
            # members' copies die with the leave's epoch bump).  Membership
            # state clears so active() turns False; change callbacks do NOT
            # fire — leaving is this peer's own decision, not a cohort event
            # it must re-elect over.
            ops, self._ops = list(self._ops.values()), {}
            self._parked.clear()
            self._ring_parked.clear()
            self._park_t.clear()
            self._seq.clear()
            self._recv_seq.clear()
            self._members = []
            self._member_hosts = {}
        for op in ops:
            op.future.set_exception(RpcError("left group"))
        done = threading.Event()
        acked = []

        def _reply(result, error):
            if error is None and isinstance(result, dict) and result.get("left"):
                acked.append(True)
            done.set()

        self._rpc.async_callback(
            self._broker_name, "__broker_leave", _reply,
            self._name, self._rpc.get_name(),
        )
        done.wait(timeout)
        return bool(acked)

    def update(self) -> None:
        """Pump: ping the broker, request resync when stale, sweep op timeouts.

        Mirrors the reference's ping-driven ``GroupService::update``
        (``src/group.h:394-490``); call it regularly from the train loop.
        """
        now = time.monotonic()
        if self._broker_addrs and not self._left:
            self._broker_maintenance(now)
        if (now - self._last_ping >= self._ping_interval and not self._ping_inflight
                and not self._left):
            self._last_ping = now
            self._ping_inflight = True
            seq = self._ping_seq
            self._rpc.async_callback(
                self._broker_name,
                "__broker_ping",
                lambda result, error: self._on_ping_reply(result, error, seq),
                self._name,
                self._rpc.get_name(),
                self._sort_order,
                self._sync_id,
                self._host_key,
                self._role,
                self._broker_gen,
            )
        with self._lock:
            expired = [
                op for op in self._ops.values() if now - op.started_at > self._timeout
            ]
            for op in expired:
                del self._ops[op.key]
            # Parked frames whose op never materialized (epoch never adopted,
            # or the local op consumed them — all_reduce pops the frame lists
            # but not the timestamps) age out on the same clock as ops.
            stale = [
                k for k, t in self._park_t.items()
                if now - t > self._timeout
                or (k not in self._parked and k not in self._ring_parked)
            ]
            for k in stale:
                del self._park_t[k]
                self._parked.pop(k, None)
                self._ring_parked.pop(k, None)
        # Futures complete outside the group lock: done-callbacks (e.g. the
        # Accumulator's) take their own locks, and completing inline would
        # invert the lock order against all_reduce callers.
        for op in expired:
            op.future.set_exception(RpcError(f"allreduce {op.key} timed out"))

    # -------------------------------------------------------- broker failover
    def _broker_maintenance(self, now: float) -> None:
        """Multi-broker upkeep (``set_brokers`` mode): resolve the broker's
        rpc name from the address list, abandon overdue pings, start and
        pump the failover scan.  Called from ``update()``."""
        sends: List[str] = []
        fo_ref: Optional[dict] = None
        with self._lock:
            if not self._broker_resolved and self._failover is None:
                for a in self._broker_addrs:
                    name = self._rpc.peer_name_at(a)
                    if name is not None:
                        # First address to greet is the presumed primary; a
                        # standby reply to the first ping corrects a wrong
                        # first guess via the failover scan.
                        self._broker_name = name
                        self._broker_resolved = True
                        break
            # An unanswered ping blocks the ping loop (and the rpc-level
            # timeout can be much longer than the failover budget): past the
            # failure window stop trusting it — the late reply, if it ever
            # lands, is ignored by the seq guard.
            if (self._ping_inflight
                    and now - self._last_ping
                    > max(self._ping_interval, self._broker_fail_after)):
                self._ping_inflight = False
                self._ping_seq += 1
                if self._ping_fail_since is None:
                    self._ping_fail_since = self._last_ping
            if (self._failover is None and self._ping_fail_since is not None
                    and now - self._ping_fail_since > self._broker_fail_after):
                self._start_failover_locked(now, "ping silence")
            fo = self._failover
            if fo is not None and fo.get("target") is None:
                fo_ref = fo
                for a in self._broker_addrs:
                    name = self._rpc.peer_name_at(a)
                    if name is None:
                        continue  # never greeted (down or still dialing)
                    if now - fo["asked"].get(name, -1e9) < 1.0:
                        continue
                    fo["asked"][name] = now
                    sends.append(name)
                replies = fo["replies"]
                if replies and (len(replies) >= len(fo["asked"])
                                or now - fo["t0"] >= 0.5):
                    # Highest generation wins; primaries beat standbys at the
                    # same generation (a fresh low-generation primary must
                    # lose to the fenced standby that outlived it); the name
                    # breaks exact ties deterministically.
                    gen, _primary, target = max(replies.values())
                    fo["target"] = target
                    self._broker_name = target
                    self._broker_resolved = True
                    self._broker_gen = max(self._broker_gen, gen)
                    self._ping_seq += 1
                    self._ping_inflight = False
                    self._last_ping = 0.0  # ping the new broker immediately
                    self._ping_fail_since = None
                    utils.log_info(
                        "group %s: failing over to broker %r (generation %d)",
                        self._name, target, gen,
                    )
        for name in sends:
            self._rpc.async_callback(
                name, "__broker_status",
                lambda result, error, name=name, fo=fo_ref:
                    self._on_status_reply(name, fo, result, error),
            )

    def _start_failover_locked(self, now: float, why: str) -> None:
        self._failover = {"t0": now, "asked": {}, "replies": {}, "target": None}
        _M_FAILOVERS.inc()
        utils.log_info(
            "group %s: broker %r unresponsive (%s) — scanning %d broker address(es)",
            self._name, self._broker_name, why, len(self._broker_addrs),
        )

    def _on_status_reply(self, name: str, fo: dict, result, error) -> None:
        if error is not None or not isinstance(result, dict):
            return
        with self._lock:
            if self._failover is not fo or fo.get("target") is not None:
                return  # a newer scan owns the state, or this one concluded
            fo["replies"][name] = (
                int(result.get("generation", 0)),
                bool(result.get("primary", False)),
                name,
            )

    def _on_ping_reply(self, result, error, seq: Optional[int] = None):
        now = time.monotonic()
        with self._lock:
            if seq is not None and seq != self._ping_seq:
                return  # abandoned cycle (overdue ping, or broker retargeted)
            self._ping_inflight = False
            if error is not None:
                if self._ping_fail_since is None:
                    self._ping_fail_since = now
                utils.log_verbose("group %s: broker ping failed: %s", self._name, error)
                return
            self._ping_fail_since = None
            if isinstance(result, dict):
                gen = result.get("generation")
                if gen is not None and int(gen) > self._broker_gen:
                    self._broker_gen = int(gen)
                if result.get("standby"):
                    # The broker we ping was demoted (or never promoted): it
                    # cannot serve epochs.  Don't wait for ping silence.
                    if self._broker_addrs and self._failover is None:
                        self._start_failover_locked(now, "standby reply")
                    return
            fo = self._failover
            if fo is not None and fo.get("target") == self._broker_name:
                # First successful ping against the newly-picked primary:
                # the control plane is back for this peer.
                self._failover = None
                dt = now - fo["t0"]
                observe_phase("broker_failover", dt)
                telemetry.flight_event("group.broker_failover",
                                       group=self._name,
                                       broker=self._broker_name,
                                       generation=self._broker_gen,
                                       seconds=round(dt, 4))
                utils.log_info(
                    "group %s: broker failover complete: %r gen=%d in %.2fs",
                    self._name, self._broker_name, self._broker_gen, dt,
                )
            elif fo is not None and fo.get("target") is None:
                # The broker answered as a primary mid-scan: it recovered
                # (or was a false alarm) — stand down the scan.
                self._failover = None
            remote_sync = result["sync_id"]
            if self._role != "member":
                # Observers are outside the epoch: the broker's sync_id is the
                # contributing cohort's, not ours — never resync over it.
                return
            stale = remote_sync != self._sync_id
            if not stale:
                self._stale_since = None
                return
            # The broker pushes updates on change; if we stay stale for more
            # than a couple of pings we likely missed the push — ask again.
            if self._stale_since is None:
                self._stale_since = now
                return
            want_resync = now - self._stale_since > 2 * self._ping_interval
        if want_resync:
            self._stale_since = None
            self._rpc.async_callback(
                self._broker_name,
                "__broker_resync",
                lambda r, e: None,
                self._name,
                self._rpc.get_name(),
            )

    # ------------------------------------------------------------ membership
    def _on_update(self, sync_id: int, members: List[str], hosts=None,
                   generation=None):
        with self._lock:
            if generation is not None:
                generation = int(generation)
                if generation < self._broker_gen:
                    # Generation fence: a zombie ex-primary (wedged process,
                    # healed partition) pushing epochs it has no right to
                    # mint.  Its sync_ids may even be higher than the real
                    # primary's — the fence, not the epoch number, is what
                    # rejects it (the real primary outruns those sync_ids on
                    # our next ping via the broker's sync_id repair).
                    _M_STALE_PUSHES.inc()
                    utils.log_verbose(
                        "group %s: rejecting push from fenced broker "
                        "(generation %d < %d)",
                        self._name, generation, self._broker_gen,
                    )
                    return None
                if generation > self._broker_gen:
                    self._broker_gen = generation
            if self._sync_id is not None and sync_id <= self._sync_id:
                return None  # stale push
            self._sync_id = sync_id
            self._members = list(members)
            self._member_hosts = dict(hosts) if hosts else {}
            self._stale_since = None
            # Cancel everything in flight: the tree changed under it
            # (reference cancels with "group change", src/group.h:453-460).
            # Frames parked FOR this very epoch survive — a fast peer's
            # first op raced ahead of our broker push (see _on_reduce);
            # everything else died with its epoch.
            ops, self._ops = list(self._ops.values()), {}
            self._parked = {k: v for k, v in self._parked.items()
                            if k[0] == sync_id}
            self._ring_parked = {k: v for k, v in self._ring_parked.items()
                                 if k[0] == sync_id}
            self._park_t = {k: t for k, t in self._park_t.items()
                            if k in self._parked or k in self._ring_parked}
            self._seq.clear()
            self._recv_seq.clear()
        telemetry.flight_event("group.epoch", group=self._name,
                               sync_id=sync_id, members=len(members),
                               cancelled_ops=len(ops))
        for op in ops:
            op.future.set_exception(RpcError("group changed"))
        for cb in self._on_change_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                utils.log_error("group change callback failed")
        utils.log_verbose(
            "group %s: sync_id=%s members=%s", self._name, sync_id, members
        )
        return None

    # -------------------------------------------------------------- topology
    def _tree(self) -> Tuple[int, Optional[int], List[int]]:
        """(my_index, parent_index, child_indices) in the current epoch."""
        me = self._rpc.get_name()
        idx = self._members.index(me)
        parent = None if idx == 0 else (idx - 1) // 2
        n = len(self._members)
        children = [c for c in (2 * idx + 1, 2 * idx + 2) if c < n]
        return idx, parent, children

    # -------------------------------------------------------------- allreduce
    def all_reduce(self, name: str, value, op="sum", finalize=None, *,
                   meta=None, meta_op=None, wire=None, chunked=None,
                   template=None, bucketed=None, chunk_align=None,
                   owned: bool = False) -> AllReduce:
        """Start an allreduce of ``value`` under ``name``; all active members
        must call with the same name (and call order per name).

        ``finalize``, if given, is applied to a tree node's reduced partial
        before it travels on the wire (and to the root's final result).  This
        lets an op accumulate in a wide dtype at each hop and re-round only
        once per hop — the Accumulator's wire-compression contract.

        Large uniform-dtype array payloads with a builtin string ``op``
        automatically take the bandwidth-optimal **chunked ring** path
        (reduce-scatter + all-gather, see ``_RingOp``) when ``ring_auto``
        says so (payload >= ``MOOLIB_RING_THRESHOLD``, cohort >= 3, spans
        more than one machine); ``chunked=True/False`` forces the choice.
        The path choice is part of the op's wire protocol, so it must be
        deterministic cohort-wide: same threshold env, same payload shapes,
        same kwargs on every peer (``ring_auto``'s other inputs come from
        the broker's epoch push and agree by construction).  Ring-only
        extras:

        - ``meta``/``meta_op``: a small side value combined exactly once per
          member along the ring (e.g. batch counts); the future then resolves
          to ``(value, meta)``.
        - ``wire``: per-hop chunk compression — a numpy dtype name (e.g.
          ``"bfloat16"``: accumulate f32, re-round per hop) or ``"q8"``
          (symmetric int8, one scale per chunk).
        - ``value=None`` (sum only) contributes zero at near-zero wire cost;
          ``template`` must then supply the pytree of array shapes.
        - ``chunk_align``: align ring chunk boundaries to multiples of this
          many ELEMENTS (the Accumulator passes its flat-bucket size so ring
          chunks land on bucket boundaries).  Wire protocol: same on every
          peer.

        Large uniform-dtype ``op="sum"`` payloads that stay on the tree take
        the **flat-bucket** path (``bucketed=True/False`` forces, ``None``
        auto-selects above ``MOOLIB_BUCKET_THRESHOLD``): the payload is
        flattened into fixed-size buckets (``buckets.bucket_bytes()``), each
        bucket rides the tree as its own pipelined sub-op, contributions are
        folded IN PLACE off the borrowed receive buffer, and the wire sees
        memoryviews over the flat buffer end to end (docs/DESIGN.md
        "Gradient data plane").  ``meta``/``wire``/``template`` compose with
        ``bucketed=True`` exactly as with the ring.  ``owned=True`` declares
        that the value's buffers belong to the op until the future resolves
        (the op may fold partial sums into them in place) and that the
        caller accepts READ-ONLY result views (the zero-copy share terminus
        may leave the result in adopted shared pages); without it the
        caller's arrays are only read and results are always writable.  Like the ring/tree choice, the
        bucket path choice and bucket size are wire protocol — identical
        settings on every peer.
        """
        future = AllReduce()
        if (meta is not None or wire is not None or template is not None) and (
            chunked is not True and bucketed is not True
        ):
            # These kwargs must not silently change meaning with cohort or
            # payload size: they require an explicit path choice.
            raise RpcError("meta=/wire=/template= require chunked=True or bucketed=True")
        with self._lock:
            # The auto decision MUST be read under the same lock acquisition
            # that assigns the op's sync_id key (RLock — ring_auto re-enters):
            # an epoch push landing between decide and register would attach
            # an old-epoch path choice to a new-epoch op key, and peers at
            # one key must always agree on the path.
            use_ring = chunked
            if use_ring is None:
                use_ring = (
                    meta is None and wire is None and template is None
                    and finalize is None and isinstance(op, str) and value is not None
                    and bucketed is not True
                    and self.ring_auto(_ring_nbytes(value))
                )
            if use_ring:
                if not isinstance(op, str):
                    raise RpcError("chunked allreduce needs a builtin string op")
                if finalize is not None:
                    raise RpcError("chunked allreduce: use wire= instead of finalize=")
                if value is None and op != "sum":
                    raise RpcError("value=None (skip) only composes with op='sum'")
                if meta is not None and meta_op is None:
                    raise RpcError("meta= requires meta_op=")
            use_buckets = False
            if not use_ring:
                use_buckets = bucketed
                if use_buckets is None:
                    # Auto rule, deterministic cohort-wide: same threshold
                    # env, same payload shapes, same member count.
                    nb = _ring_nbytes(value) if value is not None else -1
                    use_buckets = (
                        meta is None and wire is None and template is None
                        and finalize is None and op == "sum"
                        and nb >= _bucket_threshold()
                        and len(self._members) >= 2
                    )
                if use_buckets:
                    if op != "sum":
                        raise RpcError("bucketed allreduce only composes with op='sum'")
                    if finalize is not None:
                        raise RpcError("bucketed allreduce: use wire= instead of finalize=")
                    if value is None and template is None:
                        raise RpcError("bucketed allreduce with value=None requires template=")
                    if meta is not None and meta_op is None:
                        raise RpcError("meta= requires meta_op=")
            reduce_fn = None if (use_ring or use_buckets) else _resolve_op(op)
            if self._sync_id is None or self._rpc.get_name() not in self._members:
                future.set_exception(RpcError("group not active"))
                return future
            seq_key = (self._sync_id, name)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            key = (self._sync_id, name, seq)
            if len(self._members) == 1:
                future.set_result((value, meta) if meta is not None else value)
                return future
            if use_buckets:
                try:
                    finished = self._bucketed_start_locked(
                        name, seq, value, future, meta, meta_op, wire, template,
                        owned)
                except RpcError as e:
                    future.set_exception(e)
                    return future
            elif use_ring:
                try:
                    opstate = _RingOp(
                        key, value, op, future, list(self._members),
                        self._members.index(self._rpc.get_name()), wire,
                        meta, meta_op, template, chunk_align)
                except RpcError as e:
                    future.set_exception(e)
                    return future
                self._ops[key] = opstate
                for frame in self._ring_parked.pop(key, []):
                    opstate.pending[(frame[0], frame[1])] = frame[2:]
                if self._parked.pop(key, None) is not None:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: tree contribution "
                        f"received for chunked op {key}"))
                    return future
                if (self._sync_id, f"{name}\x1f{seq}:0", 0) in self._parked:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: bucketed "
                        f"contribution received for chunked op {key}"))
                    return future
            else:
                opstate = _Op(key, value, reduce_fn, finalize, future)
                self._ops[key] = opstate
                parked = self._parked.pop(key, [])
                opstate.contribs.extend(parked)
                if self._ring_parked.pop(key, None) is not None:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: ring frame "
                        f"received for tree op {key}"))
                    return future
                # Bucketed sub-ops address child keys (name\x1f<seq>:<k>,
                # child seq always 0) — a parked bucket-0 frame means a peer
                # took the bucketed path for this very round.
                if (self._sync_id, f"{name}\x1f{seq}:0", 0) in self._parked:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: bucketed "
                        f"contribution received for tree op {key}"))
                    return future
                action = self._check_op_locked(opstate)
        if use_buckets:
            for op_, action_ in finished:
                self._finish_op(op_, action_)
        elif use_ring:
            self._ring_pump(opstate)
        else:
            self._finish_op(opstate, action)
        return future

    def _bucketed_start_locked(self, name, pseq, value, future, meta, meta_op,
                               wire, template, owned):
        """Create the per-bucket eager sub-ops of one flat-bucket tree
        allreduce (caller holds the group lock; see ``_BucketedReduce``).
        Returns ``(op, action)`` pairs to finish outside the lock."""
        pkey = (self._sync_id, name, pseq)
        if (
            self._parked.pop(pkey, None) is not None
            or self._ring_parked.pop(pkey, None) is not None
        ):
            raise RpcError(
                "peers disagree on allreduce path: legacy frame "
                f"received for bucketed op {pkey}")
        parent = _BucketedReduce(
            value, meta, meta_op, wire, template, owned, self._defer)
        parent.key = pkey
        layout = parent.layout
        finished = []
        created = []
        try:
            for k in range(layout.n_buckets):
                opstate, key = self._bucketed_child_locked(
                    parent, name, pseq, k, meta, wire)
                created.append(key)
                finished.append((opstate, self._check_op_locked(opstate)))
        except Exception as e:
            # Unwind every child op already registered: an orphaned child
            # would fire parent._child_done from the timeout sweep with
            # parent.future never attached.
            for key in created:
                self._ops.pop(key, None)
            parent._recycle()
            if isinstance(e, RpcError):
                raise
            raise RpcError(f"bucketed allreduce setup failed: {e!r}")
        parent.attach(future)
        # Mismatch sentinel: a legacy peer addresses this round at the
        # PARENT key, where no bucketed sub-op lives — register the parent
        # there so _on_reduce/_on_share error the round loudly (the ring
        # contract) instead of parking the frame until the timeout sweep.
        self._ops[pkey] = parent

        def _done(pkey=pkey, parent=parent):
            with self._lock:
                if self._ops.get(pkey) is parent:
                    del self._ops[pkey]

        parent.cleanup = _done
        return finished

    def _bucketed_child_locked(self, parent, name, pseq, k, meta, wire):
        """Create and register bucket ``k``'s eager sub-op of a bucketed
        round (caller holds the group lock).  Shared by the barrier path
        (``_bucketed_start_locked`` creates every bucket at once) and the
        streaming path (``bucketed_stream`` launches buckets one at a time,
        as the caller stages them).  A parked contribution of the wrong
        length (peers with mismatched ``MOOLIB_BUCKET_BYTES``) raises here —
        callers turn that into a loud whole-round error."""
        cname = f"{name}\x1f{pseq}:{k}"
        cseq_key = (self._sync_id, cname)
        cseq = self._seq.get(cseq_key, 0)
        self._seq[cseq_key] = cseq + 1
        key = (self._sync_id, cname, cseq)
        s, e = parent.layout.bounds[k]
        val = {
            "b": parent.flat_view[s:e] if parent.flat_view is not None else None,
            "m": dict(meta) if (k == 0 and meta is not None) else None,
        }
        cf = AllReduce()
        opstate = _Op(
            key, val,
            (lambda a, b, k=k: parent._fold(k, a, b)),
            parent._fin if wire is not None else None,
            cf, eager=True,
            consume=(lambda v, k=k: parent._consume(k, v)),
        )
        self._ops[key] = opstate
        try:
            for c in self._parked.pop(key, []):
                opstate.value = opstate.op(opstate.value, c)
                opstate.folded += 1
            if self._ring_parked.pop(key, None) is not None:
                raise RpcError(
                    "peers disagree on allreduce path: ring frame "
                    f"received for bucketed op {key}")
        except Exception:
            self._ops.pop(key, None)
            raise
        cf.add_done_callback(lambda f, k=k: parent._child_done(k, f))
        return opstate, key

    def bucketed_stream(self, name: str, flat, *, meta=None, meta_op=None,
                        wire=None) -> "BucketedStream":
        """Start a flat-bucket tree allreduce whose per-bucket sub-ops
        launch INCREMENTALLY (streaming gradient pipeline, DESIGN.md §6e).

        ``flat`` is the caller's contiguous staging buffer, handed over
        ``owned=True`` (folds accumulate into it in place; results may be
        read-only views) — its CONTENTS need not be ready yet: bucket ``k``'s
        slice must be fully staged only by the time the caller invokes
        ``handle.launch(k)``.  The wire protocol is IDENTICAL to the barrier
        path (same parent seq, same child op names, same payloads) — only
        the launch times differ, so streaming and barrier peers interoperate
        within one round: a faster peer's frames for a not-yet-launched
        bucket simply park until the launch folds them.

        Returns a :class:`BucketedStream` handle; ``handle.future`` resolves
        exactly like the equivalent ``all_reduce(..., bucketed=True)``
        future once every bucket's sub-op completes.  A membership-epoch
        change mid-stream errors the round loudly: the epoch push cancels
        the launched ops (``RpcError("group changed")``) and any later
        ``launch`` raises instead of silently desyncing the cohort.
        """
        future = AllReduce()
        handle = BucketedStream(self, name, future)
        flat = np.asarray(flat)
        if flat.ndim != 1 or not flat.flags.c_contiguous:
            future.set_exception(RpcError(
                "bucketed_stream needs a contiguous 1-d flat buffer"))
            handle._dead = True
            return handle
        with self._lock:
            if self._sync_id is None or self._rpc.get_name() not in self._members:
                future.set_exception(RpcError("group not active"))
                handle._dead = True
                return handle
            seq_key = (self._sync_id, name)
            pseq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = pseq + 1
            handle._pseq = pseq
            handle._sync_id = self._sync_id
            if len(self._members) == 1:
                # Degenerate cohort: the result is the caller's own staged
                # flat.  Completion waits for handle.finish() — the buffer
                # is still being filled while buckets "launch".
                handle._degenerate = (flat, meta)
                layout = buckets.BucketLayout([np.asarray(flat).shape],
                                              np.asarray(flat).dtype)
                handle.bounds = layout.bounds
                return handle
            pkey = (self._sync_id, name, pseq)
            if (
                self._parked.pop(pkey, None) is not None
                or self._ring_parked.pop(pkey, None) is not None
            ):
                future.set_exception(RpcError(
                    "peers disagree on allreduce path: legacy frame "
                    f"received for bucketed op {pkey}"))
                handle._dead = True
                return handle
            parent = _BucketedReduce(
                flat, meta, meta_op, wire, None, True, self._defer)
            parent.key = pkey
            parent.attach(future)
            handle._parent = parent
            handle._meta = meta
            handle._wire = wire
            handle.bounds = parent.layout.bounds
            # Mismatch sentinel at the parent key, exactly as the barrier
            # path registers it (legacy frames error loudly, the timeout
            # sweep covers a round whose peers never show up).
            self._ops[pkey] = parent

            def _done(pkey=pkey, parent=parent):
                with self._lock:
                    if self._ops.get(pkey) is parent:
                        del self._ops[pkey]

            parent.cleanup = _done
        return handle

    def _stream_launch(self, handle: "BucketedStream", k: int):
        """Launch bucket ``k`` of a streaming round (its slice of the flat
        buffer is now staged).  Returns the child future, or None on the
        degenerate single-member path.  Raises RpcError when the membership
        epoch changed mid-stream — buckets partially in flight cannot be
        re-keyed to the new epoch, so the round fails loudly."""
        if handle._dead:
            raise RpcError(
                f"streaming allreduce {handle.name}: round already failed")
        if handle._degenerate is not None:
            return None
        parent = handle._parent
        with self._lock:
            if self._sync_id != handle._sync_id or parent.done:
                err = RpcError(
                    f"streaming allreduce {handle.name}: group changed with "
                    f"buckets in flight (epoch {handle._sync_id} -> "
                    f"{self._sync_id})")
                handle._dead = True
            else:
                try:
                    opstate, _key = self._bucketed_child_locked(
                        parent, handle.name, handle._pseq, k, handle._meta,
                        handle._wire)
                    action = self._check_op_locked(opstate)
                    err = None
                except Exception as e:  # noqa: BLE001 — loud whole-round error
                    err = e if isinstance(e, RpcError) else RpcError(
                        f"streaming allreduce launch failed: {e!r}")
                    handle._dead = True
        if err is not None:
            parent._fail(err)
            raise err
        self._finish_op(opstate, action)
        return opstate.future

    def _stream_finish(self, handle: "BucketedStream") -> None:
        """Caller finished staging + launching every bucket.  Only the
        degenerate single-member path has work left: resolve the future with
        the (now fully staged) local flat, mirroring all_reduce's
        single-member short-circuit."""
        if handle._degenerate is not None and not handle._dead:
            flat, meta = handle._degenerate
            handle.future.set_result((flat, meta) if meta is not None else flat)

    def _stream_abort(self, handle: "BucketedStream", err) -> None:
        """Error the streaming round from the caller's side (staging failed
        mid-stream).  Launched sub-ops keep draining into the dead parent;
        peers waiting on unlaunched buckets time out loudly — same failure
        surface as a peer crashing mid-round."""
        handle._dead = True
        if handle._parent is not None:
            handle._parent._fail(err)
        else:
            handle.future.set_exception(err)

    def _defer(self, fn, *args):
        """Run ``fn(*args)`` on the completion thread.  Bucketed rounds
        complete from inline handlers on the transport IO thread; user
        done-callbacks (arbitrary code, arbitrary locks) must never run
        there (same contract as plain handler dispatch).  A dedicated
        thread rather than the Rpc executor: completions gate the caller's
        next round, and the executor queues them behind handler dispatch
        (~3 ms under load vs ~0.1 ms here)."""
        self._completer(fn, *args)

    def _on_reduce(self, key, value):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] > self._sync_id:
                # An epoch this peer hasn't learned yet: the sender's broker
                # push beat ours and its first op raced ahead.  Dropping
                # would wedge that op (and the sender's election) until the
                # timeout sweep — the re_elect stall — so park; _on_update
                # keeps frames addressed to the epoch it installs.
                self._parked.setdefault(key, []).append(_own(value))
                self._park_t.setdefault(key, time.monotonic())
                return None
            if key[0] < self._sync_id:
                return None  # contribution from a dead epoch
            op = self._ops.get(key)
            if op is None:
                # Parked past the handler return: must own the bytes (the
                # handler runs inline with borrowed receive-buffer views).
                self._parked.setdefault(key, []).append(_own(value))
                self._park_t.setdefault(key, time.monotonic())
                return None
            if isinstance(op, (_RingOp, _BucketedReduce)):
                del self._ops[key]
                mismatch = op
            else:
                mismatch = None
                fold_err = None
                if op.eager:
                    # Fold NOW, while the borrowed view is valid: for the
                    # flat-bucket sum this is one in-place add straight off
                    # the receive buffer — no materialize, no copy.  A fold
                    # failure errors the op instead of wedging it.
                    try:
                        op.value = op.op(op.value, value)
                        op.folded += 1
                    except Exception as e:  # noqa: BLE001
                        del self._ops[key]
                        fold_err = e
                else:
                    op.contribs.append(_own(value))
                action = None if fold_err is not None else self._check_op_locked(op)
        if mismatch is not None:
            err = RpcError(
                "peers disagree on allreduce path: legacy tree contribution "
                f"received for {'bucketed' if isinstance(mismatch, _BucketedReduce) else 'chunked'} "
                f"op {key}")
            if isinstance(mismatch, _BucketedReduce):
                mismatch._fail(err)
            else:
                mismatch.future.set_exception(err)
            return None
        if fold_err is not None:
            op.future.set_exception(fold_err)
            return None
        self._finish_op(op, action)
        return None

    def _check_op_locked(self, op: _Op):
        """Reduce ready contributions; returns an action the caller performs
        *outside* the group lock (sends and future completion run caller
        callbacks / take caller locks — lock-order safety), or None."""
        idx, parent, children = self._tree()
        if op.eager:
            # Contributions were folded on arrival (_on_reduce); the op is
            # ready once every tree child has been folded in.
            if op.sent_up or op.folded < len(children):
                return None
            total = op.value
        else:
            if op.sent_up or len(op.contribs) < len(children):
                return None
            total = op.value
            for c in op.contribs[: len(children)]:
                total = op.op(total, c)
        if op.finalize is not None:
            total = op.finalize(total)
        op.sent_up = True
        if parent is None:
            # Root: reduction complete — share down the tree.
            del self._ops[op.key]
            return ("root", total, idx, self._members)
        return ("up", self._members[parent], total)

    def _finish_op(self, op: _Op, action) -> None:
        """Perform the deferred part of _check_op_locked outside the lock.
        ``members`` is the epoch snapshot taken under the lock: a concurrent
        membership change must not be observed half-way (receivers drop
        messages whose epoch key is stale, so sends to old members are safe).
        """
        if action is None:
            return
        if action[0] == "root":
            _, total, idx, members = action
            self._share_down(op.key, total, idx, members)
            op.future.set_result(total)
            return
        _, parent_name, total = action

        def _sent(result, error, op=op):
            if error is not None:
                with self._lock:
                    self._ops.pop(op.key, None)
                op.future.set_exception(RpcError(f"allreduce send failed: {error}"))

        self._rpc.async_callback(
            parent_name, "__group_reduce", _sent, self._name, op.key, total
        )

    def _on_share(self, key, result, direct: bool = False):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None
            op = self._ops.pop(key, None)
            if op is None:
                return None
            if isinstance(op, (_RingOp, _BucketedReduce)):
                mismatch = op
            else:
                mismatch = None
                # The shared result is retained (future value) and forwarded
                # down the tree: take ownership of its borrowed buffers.
                # The bucketed path's consume hook copies straight into the
                # preallocated result buffer (one pass off the receive
                # buffer) and keeps the encoded form for the forward;
                # everything else deep-copies.
                err = None
                try:
                    if op.consume is not None:
                        result, forward = op.consume(result)
                    else:
                        result = forward = _own(result)
                except Exception as e:  # noqa: BLE001 - must not wedge the op
                    err = e
                idx, _, _ = self._tree()
                members = self._members
        if mismatch is not None:
            share_err = RpcError(
                "peers disagree on allreduce path: tree share "
                f"received for {'bucketed' if isinstance(mismatch, _BucketedReduce) else 'chunked'} "
                f"op {key}")
            if isinstance(mismatch, _BucketedReduce):
                mismatch._fail(share_err)
            else:
                mismatch.future.set_exception(share_err)
            return None
        if err is not None:
            op.future.set_exception(err)
            return None
        if not direct:
            # direct=True marks a root-star share: the root already reached
            # every member; receivers must not re-forward down the tree.
            self._share_down(key, forward, idx, members)
        op.future.set_result(result)
        return None

    def _share_down(self, key, result, idx: int, members: List[str]):
        if idx == 0 and len(members) > 2 and _payload_nbytes(result) >= _memfd_min():
            others = [m for m in members if m != self._rpc.get_name()]
            if self._rpc.multicast_ready(others):
                # Root-star share over same-host memfd multicast: the result
                # serializes and is written ONCE for the whole cohort (one
                # memfd, one fd per peer) instead of being re-written at
                # every tree hop.  direct=True tells receivers not to
                # forward.  Root-local decision — no cohort agreement
                # needed: forwarding is purely receiver-side behavior.
                self._rpc.async_broadcast(
                    others, "__group_share", self._name, key, result, True
                )
                return
        n = len(members)
        for c in (2 * idx + 1, 2 * idx + 2):
            if c < n:
                self._rpc.async_callback(
                    members[c], "__group_share", lambda r, e: None, self._name, key, result
                )

    # ------------------------------------------------------------ ring path
    def _on_ring(self, key, phase, step, chunk_idx, data, meta):
        key = tuple(key) if isinstance(key, list) else key
        # Ring frames are retained in ``pending`` until their step comes up
        # (and ag-phase data is stored + forwarded): own the borrowed
        # payload views up front — the copy the old deserializer made.
        data = _own(data)
        with self._lock:
            if self._sync_id is None or key[0] > self._sync_id:
                # Not-yet-learned epoch: park, same rule as _on_reduce.
                self._ring_parked.setdefault(key, []).append(
                    (phase, step, chunk_idx, data, meta))
                self._park_t.setdefault(key, time.monotonic())
                return None
            if key[0] < self._sync_id:
                return None  # frame from a dead epoch
            op = self._ops.get(key)
            if op is None:
                self._ring_parked.setdefault(key, []).append(
                    (phase, step, chunk_idx, data, meta))
                self._park_t.setdefault(key, time.monotonic())
                return None
            if not isinstance(op, _RingOp):
                del self._ops[key]
                mismatch = op
            else:
                mismatch = None
                op.pending[(phase, step)] = (chunk_idx, data, meta)
        if mismatch is not None:
            # Complete outside the lock: done-callbacks (the Accumulator's)
            # take their own locks — inline completion would invert the lock
            # order against all_reduce callers (same rule as the timeout sweep).
            ring_err = RpcError(
                "peers disagree on allreduce path: ring frame "
                f"received for {'bucketed' if isinstance(mismatch, _BucketedReduce) else 'tree'} "
                f"op {key}")
            if isinstance(mismatch, _BucketedReduce):
                mismatch._fail(ring_err)
            else:
                mismatch.future.set_exception(ring_err)
            return None
        self._ring_pump(op)
        return None

    def _ring_pump(self, op: _RingOp) -> None:
        """Drive a ring op: drain ready steps under the lock, perform the
        resulting sends / completion outside it.  A ``pumping`` flag keeps one
        driver at a time per op (concurrent frame arrivals set ``repump``)."""
        with self._lock:
            if op.pumping:
                op.repump = True
                return
            op.pumping = True
        while True:
            with self._lock:
                op.repump = False
                if op.key not in self._ops and op.done_chunks < op.n:
                    op.pumping = False
                    return  # cancelled (epoch change / timeout / error)
                try:
                    actions = op.drain()
                except RpcError as e:
                    self._ops.pop(op.key, None)
                    op.pumping = False
                    err = e
                    break
                if any(a[0] == "done" for a in actions):
                    self._ops.pop(op.key, None)
                if not actions and not op.repump:
                    op.pumping = False
                    return
            err = None
            done = False
            for a in actions:
                if a[0] == "done":
                    done = True
                else:
                    _, phase, step, chunk_idx, data, meta = a
                    self._ring_send(op, phase, step, chunk_idx, data, meta)
            if done:
                try:
                    op.future.set_result(op.assemble())
                except Exception as e:  # noqa: BLE001 - surface assembly bugs
                    op.future.set_exception(e)
                with self._lock:
                    op.pumping = False
                return
        op.future.set_exception(err)

    def _ring_send(self, op: _RingOp, phase, step, chunk_idx, data, meta):
        nxt = op.members[(op.rank + 1) % op.n]

        def _sent(result, error, op=op):
            if error is not None:
                with self._lock:
                    self._ops.pop(op.key, None)
                op.future.set_exception(
                    RpcError(f"ring allreduce send failed: {error}"))

        self._rpc.async_callback(
            nxt, "__group_ring", _sent, self._name, op.key, phase, step,
            chunk_idx, data, meta)


class BucketedStream:
    """Caller handle of one streaming bucketed allreduce
    (:meth:`Group.bucketed_stream`): ``bounds`` is the per-bucket element
    ranges of the flat buffer (the launch units), ``launch(k)`` fires bucket
    ``k``'s sub-op once its slice is staged, ``finish()`` is called after
    the last launch, ``abort(err)`` errors the round from the caller's
    side.  ``future`` resolves like the barrier path's."""

    __slots__ = (
        "_group", "name", "future", "bounds", "_parent", "_pseq", "_sync_id",
        "_meta", "_wire", "_degenerate", "_dead",
    )

    def __init__(self, group, name, future):
        self._group = group
        self.name = name
        self.future = future
        self.bounds = ()
        self._parent = None
        self._pseq = None
        self._sync_id = None
        self._meta = None
        self._wire = None
        self._degenerate = None
        self._dead = False

    def launch(self, k: int):
        return self._group._stream_launch(self, k)

    def finish(self) -> None:
        self._group._stream_finish(self)

    def abort(self, err) -> None:
        self._group._stream_abort(self, err)
