"""Elastic peer groups and binary-tree allreduce over RPC.

Counterpart of the reference's ``GroupService``/``AllReduceService``/``Group``
(``src/group.{h,cc}``): clients ping the broker, receive membership epochs
(``sync_id``), and run allreduce over a binary tree laid out by member index —
leaf→root reduction, then the result is shared back down the same tree.
Out-of-order contributions (a peer that learned the new epoch before us) are
parked and consumed when the local operation starts (reference retry queue,
``src/group.h:662-679``).  A membership change cancels every in-flight
reduction with a "group changed" error — elasticity comes from the epoch key,
not from any attempt to patch a running reduction.

TPU note: this RPC tree is the *control/elastic* data plane (DCN-class).  For
a static cohort that forms a jax device mesh, gradient reduction should ride
XLA collectives over ICI instead — see ``moolib_tpu.parallel`` and the
Accumulator's mesh backend.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import utils
from .utils import nest
from .rpc import Future, Rpc, RpcError

_OPS: Dict[str, Callable] = {
    "sum": lambda a, b: a + b,
    "product": lambda a, b: a * b,
    "min": lambda a, b: np.minimum(a, b) if _is_arr(a) else min(a, b),
    "max": lambda a, b: np.maximum(a, b) if _is_arr(a) else max(a, b),
}


def _is_arr(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _resolve_op(op) -> Callable:
    """Builtin string ops reduce leaf-wise over pytrees; a user callable is
    applied to the *whole* contributed values (so lexicographic tuple compares
    and struct-valued reductions like the Accumulator's work — reference
    ``ReduceVariant`` custom py::object ops, ``src/group.h:230-262``)."""
    if isinstance(op, str):
        leaf_op = _OPS[op]
        return lambda a, b: nest.map_many(leaf_op, a, b)
    return op


class AllReduce(Future):
    """A future result of an AllReduce operation (same API as reference)."""


class _Op:
    __slots__ = ("key", "value", "op", "finalize", "future", "contribs", "sent_up", "started_at")

    def __init__(self, key, value, op, finalize, future):
        self.key = key
        self.value = value
        self.op = op
        self.finalize = finalize
        self.future = future
        self.contribs: List[Any] = []
        self.sent_up = False
        self.started_at = time.monotonic()


class Group:
    """A group of Rpc peers allowing coordinated AllReduce (reference API:
    update/set_broker_name/set_timeout/set_sort_order/members/sync_id/name/
    active/all_reduce)."""

    def __init__(self, rpc: Rpc, name: str):
        self._rpc = rpc
        self._name = name
        self._broker_name = "broker"
        self._timeout = 60.0
        self._sort_order = 0
        self._lock = threading.RLock()
        self._sync_id: Optional[int] = None
        self._members: List[str] = []
        self._last_ping = 0.0
        self._ping_interval = 1.0
        self._ping_inflight = False
        self._stale_since: Optional[float] = None
        self._ops: Dict[Tuple, _Op] = {}
        self._parked: Dict[Tuple, List[Any]] = {}
        self._seq: Dict[Tuple, int] = {}  # (sync_id, op name) -> next seq
        self._recv_seq: Dict[Tuple, int] = {}
        self._on_change_callbacks: List[Callable] = []
        self._register_handlers()

    # ------------------------------------------------------------------ setup
    def _register_handlers(self):
        # Several Groups can share one Rpc; handlers are defined once and
        # dispatch on the group name (first argument).
        registry = getattr(self._rpc, "_moolib_groups", None)
        if registry is None:
            registry = {}
            self._rpc._moolib_groups = registry
            rpc = self._rpc

            def dispatch(method):
                def handler(group_name, *args):
                    g = registry.get(group_name)
                    if g is None:
                        return None
                    return method(g, *args)

                return handler

            rpc.define("__group_update", dispatch(Group._on_update))
            rpc.define("__group_reduce", dispatch(Group._on_reduce))
            rpc.define("__group_share", dispatch(Group._on_share))
        if self._name in registry:
            raise RpcError(f"group {self._name!r} already exists on this Rpc")
        registry[self._name] = self

    # ------------------------------------------------------------------- api
    def set_broker_name(self, name: str) -> None:
        self._broker_name = name

    def set_timeout(self, seconds: float) -> None:
        self._timeout = float(seconds)

    def set_sort_order(self, order: int) -> None:
        self._sort_order = int(order)

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def sync_id(self):
        return self._sync_id

    def name(self) -> str:
        return self._name

    def active(self) -> bool:
        with self._lock:
            return self._sync_id is not None and self._rpc.get_name() in self._members

    def add_change_callback(self, cb: Callable) -> None:
        """Extension over the reference: observe membership epoch changes."""
        self._on_change_callbacks.append(cb)

    def update(self) -> None:
        """Pump: ping the broker, request resync when stale, sweep op timeouts.

        Mirrors the reference's ping-driven ``GroupService::update``
        (``src/group.h:394-490``); call it regularly from the train loop.
        """
        now = time.monotonic()
        if now - self._last_ping >= self._ping_interval and not self._ping_inflight:
            self._last_ping = now
            self._ping_inflight = True
            self._rpc.async_callback(
                self._broker_name,
                "__broker_ping",
                self._on_ping_reply,
                self._name,
                self._rpc.get_name(),
                self._sort_order,
                self._sync_id,
            )
        with self._lock:
            expired = [
                op for op in self._ops.values() if now - op.started_at > self._timeout
            ]
            for op in expired:
                del self._ops[op.key]
        # Futures complete outside the group lock: done-callbacks (e.g. the
        # Accumulator's) take their own locks, and completing inline would
        # invert the lock order against all_reduce callers.
        for op in expired:
            op.future.set_exception(RpcError(f"allreduce {op.key} timed out"))

    def _on_ping_reply(self, result, error):
        self._ping_inflight = False
        if error is not None:
            utils.log_verbose("group %s: broker ping failed: %s", self._name, error)
            return
        remote_sync = result["sync_id"]
        with self._lock:
            stale = remote_sync != self._sync_id
            if not stale:
                self._stale_since = None
                return
            # The broker pushes updates on change; if we stay stale for more
            # than a couple of pings we likely missed the push — ask again.
            now = time.monotonic()
            if self._stale_since is None:
                self._stale_since = now
                return
            want_resync = now - self._stale_since > 2 * self._ping_interval
        if want_resync:
            self._stale_since = None
            self._rpc.async_callback(
                self._broker_name,
                "__broker_resync",
                lambda r, e: None,
                self._name,
                self._rpc.get_name(),
            )

    # ------------------------------------------------------------ membership
    def _on_update(self, sync_id: int, members: List[str]):
        with self._lock:
            if self._sync_id is not None and sync_id <= self._sync_id:
                return None  # stale push
            self._sync_id = sync_id
            self._members = list(members)
            self._stale_since = None
            # Cancel everything in flight: the tree changed under it
            # (reference cancels with "group change", src/group.h:453-460).
            ops, self._ops = list(self._ops.values()), {}
            self._parked.clear()
            self._seq.clear()
            self._recv_seq.clear()
        for op in ops:
            op.future.set_exception(RpcError("group changed"))
        for cb in self._on_change_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                utils.log_error("group change callback failed")
        utils.log_verbose(
            "group %s: sync_id=%s members=%s", self._name, sync_id, members
        )
        return None

    # -------------------------------------------------------------- topology
    def _tree(self) -> Tuple[int, Optional[int], List[int]]:
        """(my_index, parent_index, child_indices) in the current epoch."""
        me = self._rpc.get_name()
        idx = self._members.index(me)
        parent = None if idx == 0 else (idx - 1) // 2
        n = len(self._members)
        children = [c for c in (2 * idx + 1, 2 * idx + 2) if c < n]
        return idx, parent, children

    # -------------------------------------------------------------- allreduce
    def all_reduce(self, name: str, value, op="sum", finalize=None) -> AllReduce:
        """Start an allreduce of ``value`` under ``name``; all active members
        must call with the same name (and call order per name).

        ``finalize``, if given, is applied to a tree node's reduced partial
        before it travels on the wire (and to the root's final result).  This
        lets an op accumulate in a wide dtype at each hop and re-round only
        once per hop — the Accumulator's wire-compression contract.
        """
        future = AllReduce()
        reduce_fn = _resolve_op(op)
        with self._lock:
            if self._sync_id is None or self._rpc.get_name() not in self._members:
                future.set_exception(RpcError("group not active"))
                return future
            seq_key = (self._sync_id, name)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            key = (self._sync_id, name, seq)
            if len(self._members) == 1:
                future.set_result(value)
                return future
            opstate = _Op(key, value, reduce_fn, finalize, future)
            self._ops[key] = opstate
            parked = self._parked.pop(key, [])
            opstate.contribs.extend(parked)
            action = self._check_op_locked(opstate)
        self._finish_op(opstate, action)
        return future

    def _on_reduce(self, key, value):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None  # contribution from a dead epoch
            op = self._ops.get(key)
            if op is None:
                self._parked.setdefault(key, []).append(value)
                return None
            op.contribs.append(value)
            action = self._check_op_locked(op)
        self._finish_op(op, action)
        return None

    def _check_op_locked(self, op: _Op):
        """Reduce ready contributions; returns an action the caller performs
        *outside* the group lock (sends and future completion run caller
        callbacks / take caller locks — lock-order safety), or None."""
        idx, parent, children = self._tree()
        if op.sent_up or len(op.contribs) < len(children):
            return None
        total = op.value
        for c in op.contribs[: len(children)]:
            total = op.op(total, c)
        if op.finalize is not None:
            total = op.finalize(total)
        op.sent_up = True
        if parent is None:
            # Root: reduction complete — share down the tree.
            del self._ops[op.key]
            return ("root", total, idx, self._members)
        return ("up", self._members[parent], total)

    def _finish_op(self, op: _Op, action) -> None:
        """Perform the deferred part of _check_op_locked outside the lock.
        ``members`` is the epoch snapshot taken under the lock: a concurrent
        membership change must not be observed half-way (receivers drop
        messages whose epoch key is stale, so sends to old members are safe).
        """
        if action is None:
            return
        if action[0] == "root":
            _, total, idx, members = action
            self._share_down(op.key, total, idx, members)
            op.future.set_result(total)
            return
        _, parent_name, total = action

        def _sent(result, error, op=op):
            if error is not None:
                with self._lock:
                    self._ops.pop(op.key, None)
                op.future.set_exception(RpcError(f"allreduce send failed: {error}"))

        self._rpc.async_callback(
            parent_name, "__group_reduce", _sent, self._name, op.key, total
        )

    def _on_share(self, key, result):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None
            op = self._ops.pop(key, None)
            if op is None:
                return None
            idx, _, _ = self._tree()
            members = self._members
        self._share_down(key, result, idx, members)
        op.future.set_result(result)
        return None

    def _share_down(self, key, result, idx: int, members: List[str]):
        n = len(members)
        for c in (2 * idx + 1, 2 * idx + 2):
            if c < n:
                self._rpc.async_callback(
                    members[c], "__group_share", lambda r, e: None, self._name, key, result
                )
