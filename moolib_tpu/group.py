"""Elastic peer groups and binary-tree allreduce over RPC.

Counterpart of the reference's ``GroupService``/``AllReduceService``/``Group``
(``src/group.{h,cc}``): clients ping the broker, receive membership epochs
(``sync_id``), and run allreduce over a binary tree laid out by member index —
leaf→root reduction, then the result is shared back down the same tree.
Out-of-order contributions (a peer that learned the new epoch before us) are
parked and consumed when the local operation starts (reference retry queue,
``src/group.h:662-679``).  A membership change cancels every in-flight
reduction with a "group changed" error — elasticity comes from the epoch key,
not from any attempt to patch a running reduction.

TPU note: this RPC tree is the *control/elastic* data plane (DCN-class).  For
a static cohort that forms a jax device mesh, gradient reduction should ride
XLA collectives over ICI instead — see ``moolib_tpu.parallel`` and the
Accumulator's mesh backend.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import utils
from .utils import nest
from .rpc import Future, Rpc, RpcError

_OPS: Dict[str, Callable] = {
    "sum": lambda a, b: a + b,
    "product": lambda a, b: a * b,
    "min": lambda a, b: np.minimum(a, b) if _is_arr(a) else min(a, b),
    "max": lambda a, b: np.maximum(a, b) if _is_arr(a) else max(a, b),
}


def _is_arr(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _ring_threshold() -> int:
    """Payload size (bytes) above which ``all_reduce`` auto-selects the
    chunked ring path.  Read per call so tests can force it; MUST be set
    identically on every peer (path choice is part of the op's protocol)."""
    return int(os.environ.get("MOOLIB_RING_THRESHOLD", 1 << 20))


def _ring_codec(wire):
    """(encode, decode, acc_cast) for per-hop ring wire compression.

    ``encode`` maps an accumulation-dtype chunk to its wire form before every
    hop; ``decode`` maps a wire object back to the accumulation dtype;
    ``acc_cast`` lifts a local contribution into the accumulation dtype.
    With a wire dtype set, partial sums accumulate in float32 and are
    re-rounded once per hop — the same contract as the tree's ``finalize``
    (see ``accumulator._wire_finalize``).  ``wire="q8"`` is symmetric int8
    with one scale per chunk (the per-tensor scheme of the accumulator's
    q8 path, applied at chunk granularity).
    """
    if wire is None:
        ident = lambda a: a  # noqa: E731
        return ident, ident, ident
    if wire == "q8":

        def enc(a):
            a = np.asarray(a, np.float32)
            amax = float(np.max(np.abs(a))) if a.size else 0.0
            if amax == 0.0:
                return {"q8": np.zeros(a.shape, np.int8), "s": 0.0}
            scale = amax / 127.0
            return {"q8": np.round(a / scale).astype(np.int8), "s": scale}

        def dec(obj):
            return obj["q8"].astype(np.float32) * obj["s"]

        return enc, dec, lambda a: np.asarray(a, np.float32)
    wd = np.dtype(wire)
    return (
        lambda a: np.asarray(a).astype(wd),
        lambda a: np.asarray(a).astype(np.float32),
        lambda a: np.asarray(a, np.float32),
    )


def _ring_nbytes(value) -> int:
    """Payload bytes if ring-eligible (all-array pytree, one dtype), else -1."""
    leaves = list(nest.flatten(value))
    if not leaves or not all(_is_arr(l) for l in leaves):
        return -1
    dtypes = {np.dtype(l.dtype) for l in leaves}
    if len(dtypes) != 1:
        return -1
    itemsize = dtypes.pop().itemsize
    return sum(int(l.size) for l in leaves) * itemsize


def _resolve_op(op) -> Callable:
    """Builtin string ops reduce leaf-wise over pytrees; a user callable is
    applied to the *whole* contributed values (so lexicographic tuple compares
    and struct-valued reductions like the Accumulator's work — reference
    ``ReduceVariant`` custom py::object ops, ``src/group.h:230-262``)."""
    if isinstance(op, str):
        leaf_op = _OPS[op]
        return lambda a, b: nest.map_many(leaf_op, a, b)
    return op


class AllReduce(Future):
    """A future result of an AllReduce operation (same API as reference)."""


class _Op:
    __slots__ = ("key", "value", "op", "finalize", "future", "contribs", "sent_up", "started_at")

    def __init__(self, key, value, op, finalize, future):
        self.key = key
        self.value = value
        self.op = op
        self.finalize = finalize
        self.future = future
        self.contribs: List[Any] = []
        self.sent_up = False
        self.started_at = time.monotonic()


class _RingOp:
    """State of one chunked ring allreduce (reduce-scatter + all-gather).

    Bandwidth-optimal counterpart of the reference's benchmark-only chunked
    ring (``test/test_multinode_allreduce.cc:16-150``), made a first-class
    epoch-keyed Group op: each of the N members sends ``2*(N-1)/N`` of the
    payload instead of the tree's full payload per hop (and the tree root's
    ``2x`` full payloads), so serialization cost is spread evenly across the
    cohort and chunks pipeline across ring steps.

    Protocol (rank r, ring next = (r+1) % n, chunks split near-equally):
      - reduce-scatter step s in [0, n-2]: send chunk ``(r - s) % n``
        (local contribution at s=0, accumulated partial after), receive
        chunk ``(r - 1 - s) % n`` and fold in the local contribution.
        After the last step, rank r owns the fully reduced chunk
        ``(r + 1) % n`` plus the fully combined ``meta``.
      - all-gather step s in [0, n-2]: send the completed chunk
        ``(r + 1 - s) % n``; receive ``(r - s) % n`` and forward its wire
        bytes unchanged (every rank decodes identical bytes, so wire
        compression stays bit-consistent cohort-wide).

    ``local[c] is None`` marks a zero (skip) contribution: markers forward
    without materializing zero payloads, so an all-skip round costs ~nothing
    on the wire (sum only).  Out-of-order frames park in ``pending`` keyed by
    (phase, step); steps are processed strictly in order per phase.
    """

    __slots__ = (
        "key", "future", "started_at", "members", "rank", "n", "local",
        "chunk_sizes", "dtype", "template", "leaf_shapes", "has_value",
        "enc", "dec", "acc_cast", "leaf_op", "op_name", "meta", "has_meta", "meta_op",
        "meta_total", "rs_next", "ag_next", "pending", "final", "done_chunks",
        "pumping", "repump", "sent_initial",
    )

    def __init__(self, key, value, op_name, future, members, rank, wire,
                 meta, meta_op, template):
        self.key = key
        self.future = future
        self.started_at = time.monotonic()
        self.members = members
        self.rank = rank
        self.n = len(members)
        self.enc, self.dec, self.acc_cast = _ring_codec(wire)
        self.leaf_op = _OPS[op_name]
        self.op_name = op_name
        self.meta = meta
        self.has_meta = meta is not None
        self.meta_op = meta_op
        self.meta_total = None
        self.rs_next = 0
        self.ag_next = 0
        self.pending: Dict[Tuple[str, int], Tuple] = {}
        self.final: List[Any] = [None] * self.n
        self.done_chunks = 0
        self.pumping = False
        self.repump = False
        self.sent_initial = False

        self.has_value = value is not None
        shape_src = value if value is not None else template
        if shape_src is None:
            raise RpcError("ring allreduce with value=None requires template=")
        leaves = [np.asarray(l) for l in nest.flatten(shape_src)]
        if not leaves:
            raise RpcError("ring allreduce needs at least one array leaf")
        dtypes = {l.dtype for l in leaves}
        if len(dtypes) != 1:
            raise RpcError(f"ring allreduce needs one uniform dtype, got {dtypes}")
        self.dtype = leaves[0].dtype
        self.template = shape_src
        self.leaf_shapes = [l.shape for l in leaves]
        total = sum(l.size for l in leaves)
        base, rem = divmod(total, self.n)
        self.chunk_sizes = [base + (1 if c < rem else 0) for c in range(self.n)]
        if value is not None:
            flat = np.concatenate([l.ravel() for l in leaves]) if len(leaves) > 1 \
                else leaves[0].ravel()
            self.local = []
            off = 0
            for sz in self.chunk_sizes:
                self.local.append(self.acc_cast(flat[off:off + sz]))
                off += sz
        else:
            self.local = [None] * self.n

    # -- pure state transitions (call under the group lock) -----------------
    def drain(self):
        """Process every ready pending frame; return deferred actions
        (sends / completion) for the caller to perform outside the lock."""
        actions: List[Tuple] = []
        if not self.sent_initial:
            self.sent_initial = True
            c = self.rank
            data = None if self.local[c] is None else self.enc(self.local[c])
            actions.append(("send", "rs", 0, c, data, self.meta))
        progressed = True
        while progressed:
            progressed = False
            if self.rs_next <= self.n - 2 and ("rs", self.rs_next) in self.pending:
                actions.extend(self._rs_step(*self.pending.pop(("rs", self.rs_next))))
                progressed = True
            if self.ag_next <= self.n - 2 and ("ag", self.ag_next) in self.pending:
                actions.extend(self._ag_step(*self.pending.pop(("ag", self.ag_next))))
                progressed = True
        if self.done_chunks == self.n:
            actions.append(("done",))
        return actions

    def _combine(self, incoming, c):
        mine = self.local[c]
        if incoming is None:
            return mine
        if mine is None:
            return incoming
        if (
            self.op_name == "sum"
            and isinstance(incoming, np.ndarray)
            and incoming.flags.writeable
            and incoming.dtype == np.asarray(mine).dtype
        ):
            # The decoded chunk is ours alone — accumulate in place instead
            # of allocating a fresh array every hop.
            np.add(incoming, mine, out=incoming)
            return incoming
        return self.leaf_op(incoming, mine)

    def _rs_step(self, chunk_idx, data, meta_in):
        s = self.rs_next
        self.rs_next += 1
        c = (self.rank - 1 - s) % self.n
        if chunk_idx != c:
            raise RpcError(
                f"ring protocol error: got chunk {chunk_idx} at rs step {s}, "
                f"expected {c} (peers disagree on membership?)")
        incoming = None if data is None else self.dec(data)
        if incoming is not None and incoming.size != self.chunk_sizes[c]:
            raise RpcError(
                f"ring chunk size mismatch ({incoming.size} != "
                f"{self.chunk_sizes[c]}): peers contributed different shapes")
        combined = self._combine(incoming, c)
        meta_acc = meta_in
        if self.has_meta:
            meta_acc = self.meta_op(meta_in, self.meta)
        if s == self.n - 2:
            # Chunk c is fully reduced; this rank owns it. Round-trip the
            # wire encoding so every rank decodes identical bytes.
            encoded = None if combined is None else self.enc(combined)
            self.final[c] = None if encoded is None else self.dec(encoded)
            self.meta_total = meta_acc
            self.done_chunks += 1
            return [("send", "ag", 0, c, encoded, meta_acc)]
        encoded = None if combined is None else self.enc(combined)
        return [("send", "rs", s + 1, c, encoded, meta_acc)]

    def _ag_step(self, chunk_idx, data, meta_total):
        s = self.ag_next
        self.ag_next += 1
        c = (self.rank - s) % self.n
        if chunk_idx != c:
            raise RpcError(
                f"ring protocol error: got chunk {chunk_idx} at ag step {s}, "
                f"expected {c}")
        self.final[c] = None if data is None else self.dec(data)
        if self.meta_total is None:
            self.meta_total = meta_total
        self.done_chunks += 1
        if s < self.n - 2:
            return [("send", "ag", s + 1, c, data, meta_total)]
        return []

    def assemble(self):
        """Reassemble the reduced pytree from final chunks (outside lock)."""
        if all(f is None for f in self.final):
            value = None
        else:
            parts = []
            for c, f in enumerate(self.final):
                if f is None:
                    parts.append(np.zeros(self.chunk_sizes[c], self.dtype))
                else:
                    parts.append(np.asarray(f).astype(self.dtype, copy=False))
            flat = np.concatenate(parts) if len(parts) > 1 else parts[0]
            leaves, off = [], 0
            for shape in self.leaf_shapes:
                size = int(np.prod(shape, dtype=np.int64)) if shape else 1
                leaves.append(flat[off:off + size].reshape(shape))
                off += size
            value = nest.pack_as(self.template, leaves)
        if self.has_meta:
            return value, self.meta_total
        return value


class Group:
    """A group of Rpc peers allowing coordinated AllReduce (reference API:
    update/set_broker_name/set_timeout/set_sort_order/members/sync_id/name/
    active/all_reduce)."""

    def __init__(self, rpc: Rpc, name: str):
        self._rpc = rpc
        self._name = name
        self._broker_name = "broker"
        self._timeout = 60.0
        self._sort_order = 0
        self._lock = threading.RLock()
        self._sync_id: Optional[int] = None
        self._members: List[str] = []
        self._last_ping = 0.0
        self._ping_interval = 1.0
        self._ping_inflight = False
        self._stale_since: Optional[float] = None
        self._ops: Dict[Tuple, Any] = {}  # key -> _Op | _RingOp
        self._parked: Dict[Tuple, List[Any]] = {}
        self._ring_parked: Dict[Tuple, List[Tuple]] = {}
        self._seq: Dict[Tuple, int] = {}  # (sync_id, op name) -> next seq
        self._recv_seq: Dict[Tuple, int] = {}
        self._on_change_callbacks: List[Callable] = []
        self._member_hosts: Dict[str, Optional[str]] = {}
        # Machine identity sent with every broker ping (tests override it to
        # simulate cross-host cohorts on one box).
        from .rpc.core import _boot_id

        self._host_key = _boot_id()
        self._register_handlers()

    # ------------------------------------------------------------------ setup
    def _register_handlers(self):
        # Several Groups can share one Rpc; handlers are defined once and
        # dispatch on the group name (first argument).
        registry = getattr(self._rpc, "_moolib_groups", None)
        if registry is None:
            registry = {}
            self._rpc._moolib_groups = registry
            rpc = self._rpc

            def dispatch(method):
                def handler(group_name, *args):
                    g = registry.get(group_name)
                    if g is None:
                        return None
                    return method(g, *args)

                return handler

            rpc.define("__group_update", dispatch(Group._on_update))
            rpc.define("__group_reduce", dispatch(Group._on_reduce))
            rpc.define("__group_share", dispatch(Group._on_share))
            rpc.define("__group_ring", dispatch(Group._on_ring))
        if self._name in registry:
            raise RpcError(f"group {self._name!r} already exists on this Rpc")
        registry[self._name] = self

    # ------------------------------------------------------------------- api
    def set_broker_name(self, name: str) -> None:
        self._broker_name = name

    def set_timeout(self, seconds: float) -> None:
        self._timeout = float(seconds)

    def set_sort_order(self, order: int) -> None:
        self._sort_order = int(order)

    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def member_hosts(self) -> Dict[str, Optional[str]]:
        """Machine identity (boot id) per member, from the broker's epoch
        push — every member sees the same mapping for a given ``sync_id``.
        ``None`` for members whose ping predates the host field."""
        with self._lock:
            return dict(self._member_hosts)

    def ring_auto(self, nbytes: int) -> bool:
        """The environment-aware tree-vs-ring choice for a payload of
        ``nbytes`` (VERDICT r4 weak #3: payload size alone is not enough).
        Ring when ALL of:

        - payload >= ``MOOLIB_RING_THRESHOLD`` (1 MiB default): below it the
          tree's single hop beats the ring's 2(n-1) message latency;
        - cohort size >= 3: at n=2 both algorithms move exactly one payload
          per peer and the tree is simpler;
        - the cohort spans more than one machine: same-host frames ride
          memfd zero-copy where wire bytes are nearly free, and the tree
          wins wall-clock (BENCH_LOCAL round 4); the ring's even per-peer
          load only pays on real NIC/DCN links.

        Deterministic cohort-wide: every input (threshold env, member list,
        host map) comes from the same broker epoch push, so peers at the
        same ``sync_id`` always agree — the path choice is wire protocol.
        """
        if nbytes < _ring_threshold():
            return False
        with self._lock:
            members = list(self._members)
            hosts = dict(self._member_hosts)
        if len(members) < 3:
            return False
        # "noboot-" keys are _boot_id's per-process random fallback (boot id
        # unreadable): they would make a same-host cohort look multi-machine.
        # Treat them as unknown — same policy as members with no host at all:
        # missing info must not silently disable the DCN optimization, and
        # must not manufacture a multi-host signal either.
        known = [
            None if h is None or h.startswith("noboot-") else h
            for h in (hosts.get(m) for m in members)
        ]
        if known and all(h is not None for h in known) and len(set(known)) == 1:
            return False
        return True

    def sync_id(self):
        return self._sync_id

    def name(self) -> str:
        return self._name

    def active(self) -> bool:
        with self._lock:
            return self._sync_id is not None and self._rpc.get_name() in self._members

    def add_change_callback(self, cb: Callable) -> None:
        """Extension over the reference: observe membership epoch changes."""
        self._on_change_callbacks.append(cb)

    def update(self) -> None:
        """Pump: ping the broker, request resync when stale, sweep op timeouts.

        Mirrors the reference's ping-driven ``GroupService::update``
        (``src/group.h:394-490``); call it regularly from the train loop.
        """
        now = time.monotonic()
        if now - self._last_ping >= self._ping_interval and not self._ping_inflight:
            self._last_ping = now
            self._ping_inflight = True
            self._rpc.async_callback(
                self._broker_name,
                "__broker_ping",
                self._on_ping_reply,
                self._name,
                self._rpc.get_name(),
                self._sort_order,
                self._sync_id,
                self._host_key,
            )
        with self._lock:
            expired = [
                op for op in self._ops.values() if now - op.started_at > self._timeout
            ]
            for op in expired:
                del self._ops[op.key]
        # Futures complete outside the group lock: done-callbacks (e.g. the
        # Accumulator's) take their own locks, and completing inline would
        # invert the lock order against all_reduce callers.
        for op in expired:
            op.future.set_exception(RpcError(f"allreduce {op.key} timed out"))

    def _on_ping_reply(self, result, error):
        self._ping_inflight = False
        if error is not None:
            utils.log_verbose("group %s: broker ping failed: %s", self._name, error)
            return
        remote_sync = result["sync_id"]
        with self._lock:
            stale = remote_sync != self._sync_id
            if not stale:
                self._stale_since = None
                return
            # The broker pushes updates on change; if we stay stale for more
            # than a couple of pings we likely missed the push — ask again.
            now = time.monotonic()
            if self._stale_since is None:
                self._stale_since = now
                return
            want_resync = now - self._stale_since > 2 * self._ping_interval
        if want_resync:
            self._stale_since = None
            self._rpc.async_callback(
                self._broker_name,
                "__broker_resync",
                lambda r, e: None,
                self._name,
                self._rpc.get_name(),
            )

    # ------------------------------------------------------------ membership
    def _on_update(self, sync_id: int, members: List[str], hosts=None):
        with self._lock:
            if self._sync_id is not None and sync_id <= self._sync_id:
                return None  # stale push
            self._sync_id = sync_id
            self._members = list(members)
            self._member_hosts = dict(hosts) if hosts else {}
            self._stale_since = None
            # Cancel everything in flight: the tree changed under it
            # (reference cancels with "group change", src/group.h:453-460).
            ops, self._ops = list(self._ops.values()), {}
            self._parked.clear()
            self._ring_parked.clear()
            self._seq.clear()
            self._recv_seq.clear()
        for op in ops:
            op.future.set_exception(RpcError("group changed"))
        for cb in self._on_change_callbacks:
            try:
                cb()
            except Exception:  # noqa: BLE001
                utils.log_error("group change callback failed")
        utils.log_verbose(
            "group %s: sync_id=%s members=%s", self._name, sync_id, members
        )
        return None

    # -------------------------------------------------------------- topology
    def _tree(self) -> Tuple[int, Optional[int], List[int]]:
        """(my_index, parent_index, child_indices) in the current epoch."""
        me = self._rpc.get_name()
        idx = self._members.index(me)
        parent = None if idx == 0 else (idx - 1) // 2
        n = len(self._members)
        children = [c for c in (2 * idx + 1, 2 * idx + 2) if c < n]
        return idx, parent, children

    # -------------------------------------------------------------- allreduce
    def all_reduce(self, name: str, value, op="sum", finalize=None, *,
                   meta=None, meta_op=None, wire=None, chunked=None,
                   template=None) -> AllReduce:
        """Start an allreduce of ``value`` under ``name``; all active members
        must call with the same name (and call order per name).

        ``finalize``, if given, is applied to a tree node's reduced partial
        before it travels on the wire (and to the root's final result).  This
        lets an op accumulate in a wide dtype at each hop and re-round only
        once per hop — the Accumulator's wire-compression contract.

        Large uniform-dtype array payloads with a builtin string ``op``
        automatically take the bandwidth-optimal **chunked ring** path
        (reduce-scatter + all-gather, see ``_RingOp``) when ``ring_auto``
        says so (payload >= ``MOOLIB_RING_THRESHOLD``, cohort >= 3, spans
        more than one machine); ``chunked=True/False`` forces the choice.
        The path choice is part of the op's wire protocol, so it must be
        deterministic cohort-wide: same threshold env, same payload shapes,
        same kwargs on every peer (``ring_auto``'s other inputs come from
        the broker's epoch push and agree by construction).  Ring-only
        extras:

        - ``meta``/``meta_op``: a small side value combined exactly once per
          member along the ring (e.g. batch counts); the future then resolves
          to ``(value, meta)``.
        - ``wire``: per-hop chunk compression — a numpy dtype name (e.g.
          ``"bfloat16"``: accumulate f32, re-round per hop) or ``"q8"``
          (symmetric int8, one scale per chunk).
        - ``value=None`` (sum only) contributes zero at near-zero wire cost;
          ``template`` must then supply the pytree of array shapes.
        """
        future = AllReduce()
        if (meta is not None or wire is not None or template is not None) and chunked is not True:
            # Ring-only kwargs must not silently change meaning with cohort
            # or payload size: they require the explicit chunked=True path.
            raise RpcError("meta=/wire=/template= require chunked=True")
        with self._lock:
            # The auto decision MUST be read under the same lock acquisition
            # that assigns the op's sync_id key (RLock — ring_auto re-enters):
            # an epoch push landing between decide and register would attach
            # an old-epoch path choice to a new-epoch op key, and peers at
            # one key must always agree on the path.
            use_ring = chunked
            if use_ring is None:
                use_ring = (
                    meta is None and wire is None and template is None
                    and finalize is None and isinstance(op, str) and value is not None
                    and self.ring_auto(_ring_nbytes(value))
                )
            if use_ring:
                if not isinstance(op, str):
                    raise RpcError("chunked allreduce needs a builtin string op")
                if finalize is not None:
                    raise RpcError("chunked allreduce: use wire= instead of finalize=")
                if value is None and op != "sum":
                    raise RpcError("value=None (skip) only composes with op='sum'")
                if meta is not None and meta_op is None:
                    raise RpcError("meta= requires meta_op=")
            reduce_fn = None if use_ring else _resolve_op(op)
            if self._sync_id is None or self._rpc.get_name() not in self._members:
                future.set_exception(RpcError("group not active"))
                return future
            seq_key = (self._sync_id, name)
            seq = self._seq.get(seq_key, 0)
            self._seq[seq_key] = seq + 1
            key = (self._sync_id, name, seq)
            if len(self._members) == 1:
                future.set_result((value, meta) if meta is not None else value)
                return future
            if use_ring:
                try:
                    opstate = _RingOp(
                        key, value, op, future, list(self._members),
                        self._members.index(self._rpc.get_name()), wire,
                        meta, meta_op, template)
                except RpcError as e:
                    future.set_exception(e)
                    return future
                self._ops[key] = opstate
                for frame in self._ring_parked.pop(key, []):
                    opstate.pending[(frame[0], frame[1])] = frame[2:]
                if self._parked.pop(key, None) is not None:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: tree contribution "
                        f"received for chunked op {key}"))
                    return future
            else:
                opstate = _Op(key, value, reduce_fn, finalize, future)
                self._ops[key] = opstate
                parked = self._parked.pop(key, [])
                opstate.contribs.extend(parked)
                if self._ring_parked.pop(key, None) is not None:
                    del self._ops[key]
                    future.set_exception(RpcError(
                        "peers disagree on allreduce path: ring frame "
                        f"received for tree op {key}"))
                    return future
                action = self._check_op_locked(opstate)
        if use_ring:
            self._ring_pump(opstate)
        else:
            self._finish_op(opstate, action)
        return future

    def _on_reduce(self, key, value):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None  # contribution from a dead epoch
            op = self._ops.get(key)
            if op is None:
                self._parked.setdefault(key, []).append(value)
                return None
            if isinstance(op, _RingOp):
                del self._ops[key]
                mismatch = op
            else:
                mismatch = None
                op.contribs.append(value)
                action = self._check_op_locked(op)
        if mismatch is not None:
            mismatch.future.set_exception(RpcError(
                "peers disagree on allreduce path: tree contribution "
                f"received for chunked op {key}"))
            return None
        self._finish_op(op, action)
        return None

    def _check_op_locked(self, op: _Op):
        """Reduce ready contributions; returns an action the caller performs
        *outside* the group lock (sends and future completion run caller
        callbacks / take caller locks — lock-order safety), or None."""
        idx, parent, children = self._tree()
        if op.sent_up or len(op.contribs) < len(children):
            return None
        total = op.value
        for c in op.contribs[: len(children)]:
            total = op.op(total, c)
        if op.finalize is not None:
            total = op.finalize(total)
        op.sent_up = True
        if parent is None:
            # Root: reduction complete — share down the tree.
            del self._ops[op.key]
            return ("root", total, idx, self._members)
        return ("up", self._members[parent], total)

    def _finish_op(self, op: _Op, action) -> None:
        """Perform the deferred part of _check_op_locked outside the lock.
        ``members`` is the epoch snapshot taken under the lock: a concurrent
        membership change must not be observed half-way (receivers drop
        messages whose epoch key is stale, so sends to old members are safe).
        """
        if action is None:
            return
        if action[0] == "root":
            _, total, idx, members = action
            self._share_down(op.key, total, idx, members)
            op.future.set_result(total)
            return
        _, parent_name, total = action

        def _sent(result, error, op=op):
            if error is not None:
                with self._lock:
                    self._ops.pop(op.key, None)
                op.future.set_exception(RpcError(f"allreduce send failed: {error}"))

        self._rpc.async_callback(
            parent_name, "__group_reduce", _sent, self._name, op.key, total
        )

    def _on_share(self, key, result):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None
            op = self._ops.pop(key, None)
            if op is None:
                return None
            if isinstance(op, _RingOp):
                mismatch = op
            else:
                mismatch = None
                idx, _, _ = self._tree()
                members = self._members
        if mismatch is not None:
            mismatch.future.set_exception(RpcError(
                "peers disagree on allreduce path: tree share "
                f"received for chunked op {key}"))
            return None
        self._share_down(key, result, idx, members)
        op.future.set_result(result)
        return None

    def _share_down(self, key, result, idx: int, members: List[str]):
        n = len(members)
        for c in (2 * idx + 1, 2 * idx + 2):
            if c < n:
                self._rpc.async_callback(
                    members[c], "__group_share", lambda r, e: None, self._name, key, result
                )

    # ------------------------------------------------------------ ring path
    def _on_ring(self, key, phase, step, chunk_idx, data, meta):
        key = tuple(key) if isinstance(key, list) else key
        with self._lock:
            if self._sync_id is None or key[0] != self._sync_id:
                return None  # frame from a dead epoch
            op = self._ops.get(key)
            if op is None:
                self._ring_parked.setdefault(key, []).append(
                    (phase, step, chunk_idx, data, meta))
                return None
            if not isinstance(op, _RingOp):
                del self._ops[key]
                mismatch = op
            else:
                mismatch = None
                op.pending[(phase, step)] = (chunk_idx, data, meta)
        if mismatch is not None:
            # Complete outside the lock: done-callbacks (the Accumulator's)
            # take their own locks — inline completion would invert the lock
            # order against all_reduce callers (same rule as the timeout sweep).
            mismatch.future.set_exception(RpcError(
                "peers disagree on allreduce path: ring frame "
                f"received for tree op {key}"))
            return None
        self._ring_pump(op)
        return None

    def _ring_pump(self, op: _RingOp) -> None:
        """Drive a ring op: drain ready steps under the lock, perform the
        resulting sends / completion outside it.  A ``pumping`` flag keeps one
        driver at a time per op (concurrent frame arrivals set ``repump``)."""
        with self._lock:
            if op.pumping:
                op.repump = True
                return
            op.pumping = True
        while True:
            with self._lock:
                op.repump = False
                if op.key not in self._ops and op.done_chunks < op.n:
                    op.pumping = False
                    return  # cancelled (epoch change / timeout / error)
                try:
                    actions = op.drain()
                except RpcError as e:
                    self._ops.pop(op.key, None)
                    op.pumping = False
                    err = e
                    break
                if any(a[0] == "done" for a in actions):
                    self._ops.pop(op.key, None)
                if not actions and not op.repump:
                    op.pumping = False
                    return
            err = None
            done = False
            for a in actions:
                if a[0] == "done":
                    done = True
                else:
                    _, phase, step, chunk_idx, data, meta = a
                    self._ring_send(op, phase, step, chunk_idx, data, meta)
            if done:
                try:
                    op.future.set_result(op.assemble())
                except Exception as e:  # noqa: BLE001 - surface assembly bugs
                    op.future.set_exception(e)
                with self._lock:
                    op.pumping = False
                return
        op.future.set_exception(err)

    def _ring_send(self, op: _RingOp, phase, step, chunk_idx, data, meta):
        nxt = op.members[(op.rank + 1) % op.n]

        def _sent(result, error, op=op):
            if error is not None:
                with self._lock:
                    self._ops.pop(op.key, None)
                op.future.set_exception(
                    RpcError(f"ring allreduce send failed: {error}"))

        self._rpc.async_callback(
            nxt, "__group_ring", _sent, self._name, op.key, phase, step,
            chunk_idx, data, meta)
