"""mtlint CLI (``python -m moolib_tpu.analysis [paths...]``).

Exit 0: no findings beyond the committed baseline.  Exit 1: new findings
(printed one per line, ``path:line:col: check: message``).  Exit 2: usage
errors (unknown check name, unparseable baseline).

    python -m moolib_tpu.analysis                    # lint moolib_tpu/
    python -m moolib_tpu.analysis --check bare-timer # one check only
    python -m moolib_tpu.analysis --list             # the check catalog
    python -m moolib_tpu.analysis --write-baseline   # re-grandfather
    python -m moolib_tpu.analysis --prune-baseline   # report stale entries
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from .core import (
    all_checks,
    default_baseline_path,
    lint_paths,
    load_baseline,
    write_baseline,
)


def _default_root() -> str:
    """The directory containing the ``moolib_tpu`` package = the repo root
    baselines are keyed against, wherever the lint is invoked from."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m moolib_tpu.analysis", description=__doc__
    )
    p.add_argument("paths", nargs="*", help="files/dirs (default: moolib_tpu/)")
    p.add_argument(
        "--check",
        action="append",
        default=None,
        metavar="NAME",
        help="run only these checks (repeat or comma-separate)",
    )
    p.add_argument(
        "--root", default=None, help="repo root for relative paths (default: auto)"
    )
    p.add_argument(
        "--baseline",
        default=None,
        metavar="FILE",
        help=f"baseline file (default: {os.path.basename(default_baseline_path())})",
    )
    p.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline entirely"
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write every current finding into the baseline and exit 0",
    )
    p.add_argument(
        "--prune-baseline",
        action="store_true",
        help="report baseline entries that no longer match any finding",
    )
    p.add_argument("--list", action="store_true", help="list checks and exit")
    p.add_argument(
        "-q", "--quiet", action="store_true", help="suppress the summary line"
    )
    args = p.parse_args(argv)

    registry = all_checks()
    if args.list:
        width = max(len(n) for n in registry)
        for name in sorted(registry):
            print(f"{name:<{width}}  {registry[name].description}")
        return 0

    checks: Optional[List[str]] = None
    if args.check:
        checks = [c for chunk in args.check for c in chunk.split(",") if c]
        unknown = [c for c in checks if c not in registry]
        if unknown:
            print(f"unknown check(s): {', '.join(unknown)}", file=sys.stderr)
            print(f"available: {', '.join(sorted(registry))}", file=sys.stderr)
            return 2

    root = os.path.abspath(args.root or _default_root())
    paths = list(args.paths) or [os.path.join(root, "moolib_tpu")]
    active, suppressed, broken = lint_paths(paths, root=root, checks=checks)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        write_baseline(baseline_path, active)
        print(
            f"wrote {len(active)} finding(s) to {os.path.relpath(baseline_path, root)}"
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(baseline_path)
    matched: dict = {}
    new = []
    for f in active:
        k = f.key()
        if baseline.get(k, 0) > matched.get(k, 0):
            matched[k] = matched.get(k, 0) + 1
        else:
            new.append(f)

    for f in new:
        print(f.format())
    for path in broken:
        print(f"{path}:0:0: parse-error: file could not be parsed", file=sys.stderr)

    rc = 1 if (new or broken) else 0
    if args.prune_baseline:
        stale = [k for k in baseline if k not in matched]
        for check, path, symbol, text in sorted(stale):
            where = f" [{symbol}]" if symbol else ""
            print(f"stale baseline entry: {path}: {check}: {text!r}{where}")
        rc = 1 if (rc or stale) else 0
    if not args.quiet:
        n_base = sum(matched.values())
        print(
            f"mtlint: {len(new)} new finding(s), {n_base} baselined, "
            f"{len(suppressed)} pragma-suppressed "
            f"({len(registry) if checks is None else len(checks)} check(s))",
            file=sys.stderr,
        )
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
