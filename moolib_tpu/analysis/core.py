"""mtlint core: findings, pragma suppression, baseline, runner.

The contracts the hot paths run on — donated-buffer discipline, the
zero-host-sync actor plane, the counter-based seeding contract, one-compile
steady-state loops, lock ordering across the threaded planes — were all, at
one point, enforced only by review and by counters that read wrong *after*
the regression shipped (the PR-8 epoch-push-skew wedge, the PR-4 leaked
parent that silently disabled buffer reuse).  This package turns each
contract into an AST check that runs at review time instead.

Three suppression layers, in order of preference:

1. **Fix it.**  Most findings are real.
2. **Inline pragma** — ``# mtlint: allow-<check>(reason)`` on the offending
   line (or alone on the line above).  The reason is mandatory: a pragma
   documents *why* the contract does not apply at this site, and an empty
   reason is itself reported as a ``pragma`` finding.
3. **The committed baseline** (``analysis/baseline.json``) — grandfathered
   findings from before a check existed.  The CI gate is *zero new
   violations*: anything not in the baseline fails the run.  Baseline
   entries are keyed on (check, path, enclosing symbol, stripped source
   text) so ordinary line drift does not invalidate them; entries that no
   longer match anything are reported as stale by ``--prune-baseline``.

``docs/ANALYSIS.md`` is the user-facing catalog.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Check",
    "Finding",
    "ModuleSource",
    "all_checks",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "register",
    "write_baseline",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation at one source location."""

    check: str
    path: str  # repo-root-relative, forward slashes
    line: int
    col: int
    message: str
    symbol: str = ""  # enclosing Class.function, "" at module level
    text: str = ""  # stripped source line (baseline key, survives line drift)

    def key(self) -> Tuple[str, str, str, str]:
        """Baseline identity: stable across pure line-number drift."""
        return (self.check, self.path, self.symbol, self.text)

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}:{self.col}: {self.check}: {self.message}{sym}"


class ModuleSource:
    """A parsed module plus the lookup tables every check needs: the import
    alias map (so ``from time import perf_counter as pc`` still resolves to
    ``time.perf_counter``), the enclosing-symbol map, and the pragma table."""

    def __init__(self, path: str, text: str):
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.aliases = self._collect_aliases(self.tree)
        self._symbols = self._collect_symbols(self.tree)
        self.pragmas, self.malformed_pragmas = self._collect_pragmas(self.lines)

    # -- imports ---------------------------------------------------------
    @staticmethod
    def _collect_aliases(tree: ast.AST) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        out[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def qualname(self, node: ast.AST) -> str:
        """Canonical dotted name of a Name/Attribute chain, aliases resolved
        (``np.asarray`` -> ``numpy.asarray``); "" when not a plain chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        parts.append(self.aliases.get(node.id, node.id))
        return ".".join(reversed(parts))

    # -- enclosing symbols ----------------------------------------------
    @staticmethod
    def _collect_symbols(tree: ast.AST) -> List[Tuple[int, int, str]]:
        spans: List[Tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno) or child.lineno
                    spans.append((child.lineno, end, name))
                    visit(child, name)
                else:
                    visit(child, prefix)

        visit(tree, "")
        return spans

    def symbol_at(self, line: int) -> str:
        best = ""
        for lo, hi, name in self._symbols:
            if lo <= line <= hi:
                best = name  # spans are visited outer-first; keep innermost
        return best

    # -- pragmas ---------------------------------------------------------
    _PRAGMA_RE = re.compile(r"#\s*mtlint:\s*allow-([a-z][a-z0-9-]*)\(([^)]*)\)")

    @classmethod
    def _collect_pragmas(
        cls, lines: Sequence[str]
    ) -> Tuple[Dict[Tuple[int, str], str], List[Tuple[int, str]]]:
        """{(line, check): reason} — a pragma covers its own line; a pragma
        on a line that holds nothing else also covers the next line (for
        statements too long to share a line with their excuse)."""
        table: Dict[Tuple[int, str], str] = {}
        malformed: List[Tuple[int, str]] = []
        for i, raw in enumerate(lines, start=1):
            for m in cls._PRAGMA_RE.finditer(raw):
                check, reason = m.group(1), m.group(2).strip()
                if not reason:
                    malformed.append((i, check))
                    continue
                table[(i, check)] = reason
                if raw.strip().startswith("#"):
                    table[(i + 1, check)] = reason
        return table, malformed

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Check:
    """One registered contract check.  Subclasses set ``name`` /
    ``description`` / ``scope`` and implement :meth:`run` yielding findings;
    the runner applies pragma + baseline suppression afterwards."""

    name: str = ""
    description: str = ""
    #: predicate over the repo-relative path; default = every python file
    #: under moolib_tpu/ (checks narrow this to their contract's modules).
    scope: Callable[[str], bool] = staticmethod(
        lambda path: path.startswith("moolib_tpu/")
    )

    def run(self, mod: ModuleSource, ctx: "Context") -> Iterator[Finding]:
        raise NotImplementedError

    # convenience for subclasses
    def finding(
        self, mod: ModuleSource, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(
            check=self.name,
            path=mod.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=mod.symbol_at(line),
            text=mod.line_text(line),
        )


@dataclasses.dataclass
class Context:
    """Run-wide state shared by checks (repo root for checks that read
    sibling files, e.g. metric-docs reading docs/TELEMETRY.md)."""

    root: str


_REGISTRY: Dict[str, Check] = {}


def register(check_cls) -> type:
    inst = check_cls()
    if not inst.name:
        raise ValueError(f"{check_cls.__name__} has no name")
    _REGISTRY[inst.name] = inst
    return check_cls


def all_checks() -> Dict[str, Check]:
    from . import checks as _checks  # noqa: F401  (registration side effect)

    return dict(_REGISTRY)


# -- baseline ------------------------------------------------------------


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load_baseline(path: Optional[str]) -> Dict[Tuple[str, str, str, str], int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str, str, str], int] = {}
    for e in data.get("entries", []):
        key = (e["check"], e["path"], e.get("symbol", ""), e.get("text", ""))
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[Tuple[str, str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"check": c, "path": p, "symbol": s, "text": t, "count": n}
        for (c, p, s, t), n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


# -- runner --------------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py"):
            yield p


def _run_checks_on_module(
    mod: ModuleSource, checks: Iterable[Check], ctx: Context
) -> Tuple[List[Finding], List[Finding]]:
    """Returns (active findings, pragma-suppressed findings)."""
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for check in checks:
        if not check.scope(mod.path):
            continue
        for f in check.run(mod, ctx):
            if (f.line, f.check) in mod.pragmas:
                suppressed.append(f)
            else:
                active.append(f)
    for line, check_name in mod.malformed_pragmas:
        active.append(
            Finding(
                check="pragma",
                path=mod.path,
                line=line,
                col=0,
                message=(
                    f"allow-{check_name} pragma without a reason — write "
                    f"`# mtlint: allow-{check_name}(why the contract does "
                    "not apply here)`"
                ),
                symbol=mod.symbol_at(line),
                text=mod.line_text(line),
            )
        )
    return active, suppressed


def lint_source(
    text: str,
    path: str = "moolib_tpu/snippet.py",
    checks: Optional[Sequence[str]] = None,
    root: str = ".",
) -> Tuple[List[Finding], List[Finding]]:
    """Lint a source string as if it lived at ``path`` (test/fixture entry
    point).  Returns ``(active, pragma_suppressed)`` findings."""
    registry = all_checks()
    selected = [registry[c] for c in checks] if checks else list(registry.values())
    mod = ModuleSource(path, text)
    active, suppressed = _run_checks_on_module(mod, selected, Context(root=root))
    active.sort(key=lambda f: (f.path, f.line, f.check))
    return active, suppressed


def lint_paths(
    paths: Sequence[str],
    root: str,
    checks: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Lint files/dirs.  Returns (active findings, pragma-suppressed
    findings, unparseable files).  ``root`` anchors the repo-relative paths
    findings and baselines are keyed on."""
    registry = all_checks()
    if checks:
        unknown = [c for c in checks if c not in registry]
        if unknown:
            raise KeyError(f"unknown check(s): {', '.join(unknown)}")
        selected = [registry[c] for c in checks]
    else:
        selected = list(registry.values())
    ctx = Context(root=root)
    active: List[Finding] = []
    suppressed: List[Finding] = []
    broken: List[str] = []
    for file in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(file), os.path.abspath(root))
        rel = rel.replace(os.sep, "/")
        try:
            with open(file, "r", encoding="utf-8") as f:
                text = f.read()
            mod = ModuleSource(rel, text)
        except (SyntaxError, UnicodeDecodeError, OSError):
            broken.append(rel)
            continue
        got, supp = _run_checks_on_module(mod, selected, ctx)
        active.extend(got)
        suppressed.extend(supp)
    active.sort(key=lambda f: (f.path, f.line, f.check))
    suppressed.sort(key=lambda f: (f.path, f.line, f.check))
    return active, suppressed, broken
