"""mtlint launch checks: the contracts PRs 1–12 bled for, as AST rules.

Each check names the PR that motivated it (see docs/ANALYSIS.md for the
full catalog with rationale); the scopes are the modules where the
contract actually holds, so a check never nags code the contract was
never meant to govern.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Check, Context, Finding, ModuleSource, register

# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

#: the 0 B/frame hot-path modules (PR 5/7: the one-crossing and
#: zero-crossing actor planes, PR 12: the decode loop, PR 20: the
#: device-resident replay plane — replay/host.py stays out of scope on
#: purpose, it IS the host-side numpy reference store).
HOT_PATHS = (
    "moolib_tpu/rollout.py",
    "moolib_tpu/engine/",
    "moolib_tpu/ops/",
    "moolib_tpu/envs/jax_envs.py",
    "moolib_tpu/replay/device.py",
    "moolib_tpu/replay/distributed.py",
    "moolib_tpu/replay/ingest.py",
)

#: the threaded planes where lock ordering is load-bearing (PR 8 epoch
#: push, PR 9/10 serving + broker HA, PR 12 engine service loop, PR 17
#: distributed checkpoint coordination — shard I/O must never run under
#: accumulator state the RPC handlers need).
LOCKED_PATHS = (
    "moolib_tpu/group.py",
    "moolib_tpu/serving.py",
    "moolib_tpu/accumulator.py",
    "moolib_tpu/rpc/core.py",
    "moolib_tpu/engine/",
    "moolib_tpu/rollout.py",
    "moolib_tpu/checkpoint.py",
)

#: env/rollout code bound by the counter-based seeding contract (PR 7).
RNG_PATHS = ("moolib_tpu/envs/", "moolib_tpu/rollout.py")


def _in(path: str, prefixes: Sequence[str]) -> bool:
    return any(path.startswith(p) for p in prefixes)


def _call_name(mod: ModuleSource, call: ast.Call) -> str:
    return mod.qualname(call.func)


def _jit_donations(mod: ModuleSource) -> Dict[str, Tuple[int, ...]]:
    """``{callable name: donated positional indices}`` for every
    ``x = jax.jit(..., donate_argnums=...)`` (plain or ``self.x``) in the
    module, plus plain ``jax.jit`` bindings with no donation (empty tuple)
    so recompile-risk knows what is jitted."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        if mod.qualname(node.value.func) != "jax.jit":
            continue
        donated: Tuple[int, ...] = ()
        for kw in node.value.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    donated = (v.value,)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    donated = tuple(
                        e.value
                        for e in v.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, int)
                    )
        for tgt in node.targets:
            name = ast.unparse(tgt) if isinstance(tgt, (ast.Name, ast.Attribute)) else ""
            if name:
                out[name] = donated
    return out


def _functions(mod: ModuleSource) -> Iterator[ast.AST]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# host-sync (PR 5/7: the 0 B/frame contract)
# ---------------------------------------------------------------------------


@register
class HostSyncCheck(Check):
    name = "host-sync"
    description = (
        "device_get / block_until_ready / np.asarray / scalar coercion of a "
        "computation inside the hot-path modules — every one is a host "
        "round-trip the 0 B/frame actor plane and the one-compile decode "
        "loop must not take per frame"
    )
    scope = staticmethod(lambda path: _in(path, HOT_PATHS))

    _FUNCS = {
        "jax.device_get": "jax.device_get forces a device->host transfer",
        "jax.block_until_ready": "jax.block_until_ready stalls dispatch on device completion",
        "numpy.asarray": "np.asarray on a device value is a blocking D2H copy",
        "numpy.array": "np.array on a device value is a blocking D2H copy",
        "numpy.copy": "np.copy on a device value is a blocking D2H copy",
    }
    _METHODS = {
        "block_until_ready": ".block_until_ready() stalls dispatch on device completion",
        "item": ".item() synchronously fetches a device scalar",
    }
    #: inner calls whose scalar coercion is host arithmetic, not a device
    #: sync: builtins over python ints and environment/config parsing.
    _HOST_SCALAR_CALLS = {
        "min", "max", "len", "round", "abs", "divmod",
        "os.environ.get", "os.getenv",
    }

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = _call_name(mod, node)
            if qual in self._FUNCS:
                yield self.finding(mod, node, self._FUNCS[qual])
                continue
            if isinstance(node.func, ast.Attribute) and node.func.attr in self._METHODS:
                yield self.finding(mod, node, self._METHODS[node.func.attr])
                continue
            # float(f(x)) / int(x.sum()): coercing the *result of a call* to
            # a python scalar synchronizes on the whole computation.
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Call)
                and _call_name(mod, node.args[0]) not in self._HOST_SCALAR_CALLS
                and not _call_name(mod, node.args[0]).startswith("math.")
            ):
                yield self.finding(
                    mod,
                    node,
                    f"{node.func.id}() of a call result synchronously coerces "
                    "a device scalar to host",
                )


# ---------------------------------------------------------------------------
# donation-safety (PR 5: the donated-buffer carry contract)
# ---------------------------------------------------------------------------


@register
class DonationSafetyCheck(Check):
    name = "donation-safety"
    description = (
        "a variable passed at a donated position of a jax.jit(..., "
        "donate_argnums=...) callable is read again afterwards in the same "
        "function — donated buffers are dead the moment the call is issued"
    )

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        donations = {k: v for k, v in _jit_donations(mod).items() if v}
        if not donations:
            return
        for fn in _functions(mod):
            yield from self._check_function(mod, fn, donations)

    def _check_function(
        self, mod: ModuleSource, fn: ast.AST, donations: Dict[str, Tuple[int, ...]]
    ) -> Iterator[Finding]:
        # (donated var, line of donating call) pairs found in this function.
        donated_at: List[Tuple[str, int, str]] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = (
                ast.unparse(node.func)
                if isinstance(node.func, (ast.Name, ast.Attribute))
                else ""
            )
            positions = donations.get(callee)
            if not positions:
                continue
            for p in positions:
                if p < len(node.args) and isinstance(node.args[p], ast.Name):
                    donated_at.append((node.args[p].id, node.lineno, callee))
        if not donated_at:
            return
        loads: Dict[str, List[int]] = {}
        stores: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                bucket = loads if isinstance(node.ctx, ast.Load) else stores
                bucket.setdefault(node.id, []).append(node.lineno)
        for var, call_line, callee in donated_at:
            # `buf = step(buf)` rebinds the name to the fresh result — the
            # canonical donation pattern, safe by construction.
            if call_line in stores.get(var, []):
                continue
            rebinds = [ln for ln in stores.get(var, []) if ln > call_line]
            horizon = min(rebinds) if rebinds else float("inf")
            bad = [ln for ln in loads.get(var, []) if call_line < ln < horizon]
            if bad:
                yield Finding(
                    check=self.name,
                    path=mod.path,
                    line=min(bad),
                    col=0,
                    message=(
                        f"`{var}` was donated to {callee}() on line "
                        f"{call_line} and read again here — the buffer may "
                        "already be aliased by the callee's output"
                    ),
                    symbol=mod.symbol_at(min(bad)),
                    text=mod.line_text(min(bad)),
                )


# ---------------------------------------------------------------------------
# raw-rng (PR 7: the counter-based seeding contract)
# ---------------------------------------------------------------------------


@register
class RawRngCheck(Check):
    name = "raw-rng"
    description = (
        "jax.random.PRNGKey / global np.random state in env or rollout code "
        "— keys must be *derived* (fold_in on episode/env counters, or a "
        "seeded Generator handed in) so host and device replays stay "
        "bit-identical"
    )
    scope = staticmethod(lambda path: _in(path, RNG_PATHS))

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = _call_name(mod, node)
            if qual == "jax.random.PRNGKey":
                yield self.finding(
                    mod,
                    node,
                    "fresh PRNGKey in env/rollout code — derive keys from "
                    "the carried key via fold_in (the seeding contract) "
                    "instead of minting new roots",
                )
            elif qual == "numpy.random.default_rng":
                if not node.args and not node.keywords:
                    yield self.finding(
                        mod,
                        node,
                        "unseeded np.random.default_rng() — host envs must "
                        "derive their stream from the seed handed in",
                    )
            elif qual.startswith("numpy.random."):
                yield self.finding(
                    mod,
                    node,
                    f"global-state {qual.replace('numpy', 'np')} — draw from "
                    "a per-env seeded Generator instead",
                )


# ---------------------------------------------------------------------------
# recompile-risk (PR 5/12: one-compile steady-state loops)
# ---------------------------------------------------------------------------


@register
class RecompileRiskCheck(Check):
    name = "recompile-risk"
    description = (
        "a python-varying scalar (loop index, len(), wall-clock) flows into "
        "a jitted steady-state call — each distinct static value is a fresh "
        "trace+compile (the engine asserts cache_size==1 for a reason)"
    )

    _VARYING_CALLS = {
        "len",
        "time.monotonic",
        "time.time",
        "time.perf_counter",
        "time.perf_counter_ns",
    }

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        jitted = set(_jit_donations(mod))
        for fn in _functions(mod):
            # loop variables live for the span of their for statement
            loop_spans: List[Tuple[str, int, int]] = []
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    end = getattr(node, "end_lineno", node.lineno) or node.lineno
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name):
                            loop_spans.append((t.id, node.lineno, end))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = (
                    ast.unparse(node.func)
                    if isinstance(node.func, (ast.Name, ast.Attribute))
                    else ""
                )
                if callee not in jitted and not callee.endswith("_jit"):
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name) and any(
                        name == arg.id and lo <= node.lineno <= hi
                        for name, lo, hi in loop_spans
                    ):
                        yield self.finding(
                            mod,
                            node,
                            f"loop variable `{arg.id}` flows into jitted "
                            f"{callee}() — hash-static per value, so every "
                            "iteration risks a retrace",
                        )
                    elif (
                        isinstance(arg, ast.Call)
                        and _call_name(mod, arg) in self._VARYING_CALLS
                    ):
                        yield self.finding(
                            mod,
                            node,
                            f"{_call_name(mod, arg)}() result flows into "
                            f"jitted {callee}() — a python-varying scalar "
                            "is a retrace per distinct value",
                        )


# ---------------------------------------------------------------------------
# bare-timer (PR 1: every timing block must reach the exporters)
# ---------------------------------------------------------------------------


@register
class BareTimerCheck(Check):
    name = "bare-timer"
    description = (
        "hand-rolled time.perf_counter{,_ns} timing outside telemetry/ and "
        "utils/profiling.py — invisible to every exporter; use "
        "telemetry spans / Histogram.time() / StepTimer (the AST walk also "
        "catches `from time import perf_counter as x` aliases the old shell "
        "grep missed)"
    )
    scope = staticmethod(
        lambda path: path.startswith("moolib_tpu/")
        and not path.startswith("moolib_tpu/telemetry/")
        and path != "moolib_tpu/utils/profiling.py"
    )

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = _call_name(mod, node)
            if qual in ("time.perf_counter", "time.perf_counter_ns"):
                yield self.finding(
                    mod,
                    node,
                    f"bare {qual}() — time through telemetry spans / "
                    "Histogram.time() / StepTimer so the block is visible "
                    "to the exporters",
                )


# ---------------------------------------------------------------------------
# blocking-under-lock (PR 8/9/10: the threaded RPC/group/serving planes)
# ---------------------------------------------------------------------------

_LOCKISH = re.compile(r"(^|[._])(lock|cond|mutex|mu)\b", re.IGNORECASE)


@register
class BlockingUnderLockCheck(Check):
    name = "blocking-under-lock"
    description = (
        "an RPC send, future .result()/.wait(), sleep, or device sync while "
        "holding a Lock/Condition — the handler or transport thread that "
        "would unblock it may need the same lock (the ABBA half of what "
        "testing.lockgraph catches at runtime)"
    )
    scope = staticmethod(lambda path: _in(path, LOCKED_PATHS))

    _BLOCKING_FUNCS = {
        "time.sleep": "time.sleep holds the lock for the whole nap",
        "jax.device_get": "jax.device_get blocks on a D2H transfer",
        "jax.block_until_ready": "jax.block_until_ready stalls on the device",
    }
    _BLOCKING_METHODS = {
        "result": "Future.result() can wait a full timeout",
        "wait": "waiting on a different primitive while holding this lock",
        "wait_for": "waiting on a different primitive while holding this lock",
        "call": "a synchronous RPC call round-trips the network",
        "sync_call": "a synchronous RPC call round-trips the network",
        "send_frame": "a transport send can block on a full socket",
        "block_until_ready": "stalls on the device",
    }

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        yield from self._walk_stmts(mod, mod.tree.body, [])

    def _walk_stmts(
        self, mod: ModuleSource, stmts: Sequence[ast.stmt], held: List[str]
    ) -> Iterator[Finding]:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def executes later, outside this lock scope
                yield from self._walk_stmts(mod, st.body, [])
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                acquired = [
                    ast.unparse(item.context_expr)
                    for item in st.items
                    if _LOCKISH.search(ast.unparse(item.context_expr))
                ]
                if held:
                    for item in st.items:
                        yield from self._scan_expr(mod, item.context_expr, held)
                yield from self._walk_stmts(mod, st.body, held + acquired)
                continue
            # any other statement: scan its own expressions (excluding
            # nested statement bodies, which recurse below — each call is
            # visited exactly once)
            if held:
                yield from self._scan_stmt(mod, st, held)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    yield from self._walk_stmts(mod, sub, held)
            for handler in getattr(st, "handlers", ()):
                yield from self._walk_stmts(mod, handler.body, held)

    def _scan_stmt(
        self, mod: ModuleSource, st: ast.stmt, held: List[str]
    ) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(st):
            if not isinstance(child, (ast.stmt, ast.excepthandler)):
                yield from self._scan_expr(mod, child, held)

    def _scan_expr(
        self, mod: ModuleSource, top: ast.AST, held: List[str]
    ) -> Iterator[Finding]:
        stack: List[ast.AST] = [top]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.stmt, ast.Lambda)):
                continue  # lambda bodies execute later; stmts recurse above
            if isinstance(node, ast.Call):
                f = self._classify(mod, node, held)
                if f is not None:
                    yield f
            stack.extend(ast.iter_child_nodes(node))

    def _classify(
        self, mod: ModuleSource, node: ast.Call, held: List[str]
    ) -> Optional[Finding]:
        qual = _call_name(mod, node)
        lockset = ", ".join(held)
        if qual in self._BLOCKING_FUNCS:
            return self.finding(
                mod,
                node,
                f"{self._BLOCKING_FUNCS[qual]} (holding {lockset})",
            )
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        if meth not in self._BLOCKING_METHODS:
            return None
        recv = ast.unparse(node.func.value)
        if meth in ("wait", "wait_for") and recv in held:
            return None  # Condition.wait on the held condition RELEASES it
        if meth == "call" and recv in ("super()",):
            return None
        if (
            meth == "result"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == 0
        ):
            return None  # .result(0) cannot block: raises if not yet done
        return self.finding(
            mod,
            node,
            f".{meth}() — {self._BLOCKING_METHODS[meth]} (holding {lockset})",
        )


# ---------------------------------------------------------------------------
# metric-docs (PR 1/11: docs/TELEMETRY.md is the metric contract)
# ---------------------------------------------------------------------------


@register
class MetricDocsCheck(Check):
    name = "metric-docs"
    description = (
        "every registry.counter/gauge/histogram name registered in code "
        "must appear (backticked) in a docs/TELEMETRY.md table row — the "
        "doc tables are the queryable metric contract"
    )

    def _doc_tables(self, ctx: Context) -> Optional[str]:
        cached = getattr(ctx, "_metric_doc_tables", None)
        if cached is not None:
            return cached or None
        path = os.path.join(ctx.root, "docs", "TELEMETRY.md")
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            ctx._metric_doc_tables = ""  # absent docs: check is dormant
            return None
        tables = "\n".join(l for l in text.splitlines() if l.lstrip().startswith("|"))
        ctx._metric_doc_tables = tables
        return tables

    def run(self, mod: ModuleSource, ctx: Context) -> Iterator[Finding]:
        tables = self._doc_tables(ctx)
        if tables is None:
            return
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if f"`{name}`" not in tables:
                yield self.finding(
                    mod,
                    node,
                    f"metric `{name}` ({node.func.attr}) is not documented "
                    "in any docs/TELEMETRY.md table row",
                )
