"""mtlint — the project contract lint (``python -m moolib_tpu.analysis``).

Stdlib-only on purpose: the lint runs in CI before anything heavy imports,
and it must be able to *parse* modules whose runtime dependencies (jax,
numpy) it never needs.  See :mod:`moolib_tpu.analysis.core` for the
finding/pragma/baseline machinery, :mod:`moolib_tpu.analysis.checks` for
the check catalog, and ``docs/ANALYSIS.md`` for the user guide.
"""

from .core import (  # noqa: F401
    Check,
    Finding,
    all_checks,
    lint_paths,
    lint_source,
    load_baseline,
    register,
    write_baseline,
)
from .cli import main  # noqa: F401

__all__ = [
    "Check",
    "Finding",
    "all_checks",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "main",
    "register",
    "write_baseline",
]
