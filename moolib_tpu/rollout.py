"""Device-resident actor rollout buffers (the Podracer/Sebulba data plane).

The legacy actor path (``examples/vtrace/experiment.py`` host-batcher branch)
moves every observation across the host↔device boundary three times, in
float32: a host ``astype(np.float32)`` before upload (4x the H2D bytes of the
uint8 frame the env produced), a D2H when the host time-batcher stacks the
step back into an unroll, and a second H2D when the assembled learner batch
reaches the device.  On a colocated chip those are wasted DMAs; through a
dispatch tunnel they are the whole agent (VERDICT round 5: 74.9 env_frames/s
end-to-end vs 84k learner-only).

This module keeps the rollout on the device instead (arXiv:2104.06272 §
Sebulba: "rollouts are built in device memory"):

- one ``[T+1, B, ...]`` buffer pytree lives in device memory; the fused,
  jitted act step writes timestep ``t`` into it with
  ``jax.lax.dynamic_update_slice_in_dim`` and the buffer is **donated**, so
  XLA updates it in place instead of reallocating 6 arrays per step;
- the observation crosses the boundary **once, in its native dtype** (uint8
  frames stay uint8 — normalization is the model's on-chip ``astype/255``);
- the PRNG key is carried on-device through the fused step (the per-step
  ``jax.random.split`` host dispatch disappears; the split happens inside
  the same executable, producing bit-identical keys);
- the action comes back as a device array whose D2H transfer is started
  with ``copy_to_host_async()`` at dispatch time; :class:`PendingAction`
  realizes it as late as possible so ``EnvPool.step`` submission stops
  serializing behind a blocking ``np.asarray`` (dispatch is decoupled from
  fetch — the ``actor_act_dispatch_depth`` gauge counts in-flight actions,
  and realize time is accounted separately from dispatch time so the
  ``act`` timer stays honest under async dispatch);
- a completed unroll is handed over as a device pytree (consumed by the
  :class:`~moolib_tpu.batcher.Batcher` device-side path, which assembles
  learner batches by on-device cat/split — no further crossing), and the
  carried last timestep seeds the next buffer through a small **non**-donated
  jit, so the completed unroll stays valid while the fresh buffer is
  donated onwards (the donation-safety contract ``tests/test_rollout.py``
  locks down).

Bit-exactness: the fused step computes ``model.apply`` on the same float32
values the legacy path uploads (uint8 -> f32 is exact) and splits the key
with the same function, so device-rollout trajectories are bit-identical to
the legacy host-batcher path — ``tests/test_rollout.py`` compares
obs/actions/logits/core state with ``array_equal``.

Telemetry (docs/TELEMETRY.md): ``actor_h2d_bytes_total`` /
``actor_d2h_bytes_total`` / ``actor_frames_total`` make the one-crossing
contract a measured artifact (``benchmarks/agent_bench.py`` reports
``host_boundary_bytes_per_frame`` from them); ``actor_act_dispatch_seconds``
vs ``actor_act_realize_seconds`` split the old ``act`` wall time into its
dispatch and fetch halves.

:class:`AnakinRollout` goes one step further (arXiv:2104.06272 § Anakin):
when the env itself is a pure-JAX function (``envs.jax_envs``), ``env.step``
fuses INTO the jitted act step — observation, action, and reward never exist
on the host, auto-reset happens on device, and a ``lax.scan`` fast path
produces a completed ``[T+1, B]`` unroll in ONE dispatch.  The rollout loop
moves **zero host-boundary bytes per frame**: ``actor_h2d/d2h_bytes_total``
stay untouched; only the occasional episode-stats snapshot crosses, on its
own counter (``actor_stats_d2h_bytes_total``) so the per-frame contract
stays a measured zero.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry
from .telemetry import devmon

_REG = telemetry.get_registry()
# Host-boundary accounting: every byte the actor path moves between host and
# device, by direction.  The legacy path increments these too (via
# count_h2d/count_d2h at its conversion sites), so the two rollout modes are
# comparable on one metric family.
_M_H2D = _REG.counter(
    "actor_h2d_bytes_total", "actor-path bytes uploaded host -> device"
)
_M_D2H = _REG.counter(
    "actor_d2h_bytes_total", "actor-path bytes fetched device -> host"
)
_M_FRAMES = _REG.counter(
    "actor_frames_total", "env frames through the actor path (for bytes/frame)"
)
_M_DISPATCH = _REG.histogram(
    "actor_act_dispatch_seconds", "act step dispatch (enqueue, not compute)"
)
_M_REALIZE = _REG.histogram(
    "actor_act_realize_seconds", "pending action realize (D2H completion wait)"
)
_M_DEPTH = _REG.gauge(
    "actor_act_dispatch_depth", "act steps dispatched but not yet realized"
)
_M_UNROLLS = _REG.counter("actor_unrolls_total", "completed [T+1, B] unrolls")
_M_STATS_D2H = _REG.counter(
    "actor_stats_d2h_bytes_total",
    "episode-stats snapshot fetches (Anakin; outside the per-frame loop)",
)


def count_h2d(nbytes: int) -> None:
    """Record an actor-path host->device crossing (legacy path call sites)."""
    _M_H2D.inc(nbytes)


def count_d2h(nbytes: int) -> None:
    """Record an actor-path device->host crossing (legacy path call sites)."""
    _M_D2H.inc(nbytes)


def count_frames(n: int) -> None:
    _M_FRAMES.inc(n)


class PendingAction:
    """A dispatched-but-not-realized action batch.

    Holds the device array with its ``copy_to_host_async()`` already issued;
    :meth:`realize` blocks only on whatever is still outstanding (ideally
    nothing — the transfer overlapped the host work since dispatch) and
    returns host numpy.  ``EnvPool.step`` also accepts the device array (or
    this object) directly; realizing explicitly keeps the fetch wait visible
    to the ``act_fetch`` timer/watchdog section instead of hiding it inside
    the env seam.
    """

    __slots__ = ("_dev", "_host")

    def __init__(self, action_dev):
        self._dev = action_dev
        self._host: Optional[np.ndarray] = None
        if hasattr(action_dev, "copy_to_host_async"):
            action_dev.copy_to_host_async()
        _M_DEPTH.inc()

    def realize(self) -> np.ndarray:
        if self._host is None:
            t0 = time.monotonic()
            # host_span marks this D2H wait as host-blocked for any open
            # timeline capture window (telemetry.timeline).
            with telemetry.timeline.host_span("rollout.act_fetch"):
                # mtlint: allow-host-sync(the realize seam IS the intentional D2H, counted on actor_d2h_bytes_total)
                self._host = np.asarray(self._dev)
            _M_REALIZE.observe(time.monotonic() - t0)
            _M_D2H.inc(self._host.nbytes)
            _M_DEPTH.dec()
        return self._host

    def __array__(self, dtype=None):
        out = self.realize()
        return out if dtype is None else out.astype(dtype, copy=False)

    @property
    def device_array(self):
        return self._dev


# One compiled (step, carry) pair per distinct rollout geometry: several
# actor batches of the same experiment share executables instead of
# compiling per DeviceRollout instance.  Keyed on the flax module (a frozen
# dataclass, hashable by config) + shapes/dtypes.
_JIT_CACHE: Dict[Tuple, Tuple[Any, Any]] = {}


def _build_jits(model, unroll_length: int):
    def _step(params, buf, t, state, reward, done, prev_action, core_state, rng):
        # Same split the legacy host loop performs per step — inside the
        # executable, so the key never leaves the device.
        rng, act_rng = jax.random.split(rng)
        inputs = {
            # On-chip normalization: uint8 -> f32 is exact, so the model sees
            # bit-identical values to the legacy host astype(np.float32).
            "state": state.astype(jnp.float32)[None],
            "reward": reward[None],
            "done": done[None],
            "prev_action": prev_action[None],
        }
        out, new_core = model.apply(params, inputs, core_state, sample_rng=act_rng)
        action = out["action"][0]
        logits = out["policy_logits"][0]
        row = {
            "state": state,  # native dtype: the buffer stores what the env sent
            "reward": reward,
            "done": done,
            "prev_action": prev_action,
            "action": action,
            "policy_logits": logits,
        }
        buf = {
            k: jax.lax.dynamic_update_slice_in_dim(buf[k], row[k][None], t, axis=0)
            for k in buf
        }
        return buf, action, new_core, rng

    def _carry(buf):
        # Seed the next unroll with the completed one's last timestep
        # (reference carry-over).  NOT donated: the completed buffer is the
        # learner's unroll and must outlive this copy.
        return {k: jnp.zeros_like(v).at[0].set(v[unroll_length]) for k, v in buf.items()}

    # Recompile detector (telemetry.devmon): a geometry change slipping
    # through the cache key would silently recompile per call here.
    return (
        devmon.instrument_jit(jax.jit(_step, donate_argnums=(1,)), "rollout.step"),
        devmon.instrument_jit(jax.jit(_carry), "rollout.carry"),
    )


class DeviceRollout:
    """Per-actor-batch device-resident rollout state.

    Drop-in replacement for the host-batcher bookkeeping in
    ``examples.common.EnvBatchState``: owns the ``[T+1, B, ...]`` device
    buffer, the carried LSTM core, the on-device previous action, and the
    unroll boundary logic (carry last step into the next buffer, track the
    initial core state entering each unroll).

    Usage per act step::

        pending, rng = roll.step(params, obs, rng)   # obs: EnvPool views
        ...                                          # overlap host work here
        env.step(batch, pending.realize())
        unroll = roll.take_unroll()                  # device pytree or None
        if unroll is not None:
            learn_batcher.cat(unroll)                # on-device assembly
            core_batcher.cat(roll.completed_initial_core)
    """

    def __init__(self, model, batch_size: int, unroll_length: int,
                 obs_shape: Tuple[int, ...], obs_dtype, num_actions: int):
        self.batch_size = batch_size
        self.unroll_length = unroll_length
        self._obs_dtype = np.dtype(obs_dtype)
        if self._obs_dtype == np.float64:
            # x64 is disabled on the device: stage f64 env vectors as f32 on
            # the host (same cast the legacy path makes) instead of letting
            # jit canonicalize a 2x-wide upload.
            self._obs_dtype = np.dtype(np.float32)
        key = (model, batch_size, unroll_length, tuple(obs_shape),
               self._obs_dtype.str, int(num_actions))
        jits = _JIT_CACHE.get(key)
        if jits is None:
            jits = _JIT_CACHE.setdefault(key, _build_jits(model, unroll_length))
        self._step_jit, self._carry_jit = jits
        T1 = unroll_length + 1
        B = batch_size
        self._buf = {
            "state": jnp.zeros((T1, B, *obs_shape), self._obs_dtype),
            "reward": jnp.zeros((T1, B), jnp.float32),
            "done": jnp.zeros((T1, B), bool),
            "prev_action": jnp.zeros((T1, B), jnp.int32),
            "action": jnp.zeros((T1, B), jnp.int32),
            "policy_logits": jnp.zeros((T1, B, num_actions), jnp.float32),
        }
        self._t = 0
        self.core_state = model.initial_state(batch_size)
        self.prev_action = jnp.zeros((B,), jnp.int32)
        # Initial LSTM state entering the unroll currently being filled.
        self._initial_core = self.core_state
        self._completed: Optional[dict] = None
        self.completed_initial_core = None

    def step(self, params, obs: Dict[str, np.ndarray], rng):
        """Upload one env observation batch (single crossing, native dtype),
        run the fused act step, and return ``(PendingAction, rng)``.

        ``rng`` is the carried device key; the split happens inside the
        executable.  The returned pending action's D2H is already issued.
        """
        t0 = time.monotonic()
        # mtlint: allow-host-sync(obs leaves are EnvPool shm views, already host memory — asarray is a view)
        state = np.asarray(obs["state"])
        if state.dtype != self._obs_dtype:
            # Non-uint8 envs (e.g. float64 gym vectors): cast on host once to
            # the buffer dtype — still a single crossing.
            state = state.astype(self._obs_dtype)
        reward = np.asarray(obs["reward"], np.float32)  # mtlint: allow-host-sync(host shm view, see above)
        done = np.asarray(obs["done"], bool)  # mtlint: allow-host-sync(host shm view, see above)
        # THE crossing: the host arrays go straight into the fused call —
        # the jit C++ fastpath uploads them inline (native dtype, one DMA
        # per leaf), an order of magnitude cheaper per step than an
        # explicit python-side device_put.
        _M_H2D.inc(state.nbytes + reward.nbytes + done.nbytes)
        _M_FRAMES.inc(self.batch_size)
        core_before = self.core_state
        self._buf, action, self.core_state, rng = self._step_jit(
            params, self._buf, self._t, state, reward, done,
            self.prev_action, self.core_state, rng,
        )
        self.prev_action = action
        if self._t == self.unroll_length:
            # Index T written: the unroll is complete.  Hand it over and
            # seed the next buffer from its last step via the non-donated
            # carry (the completed pytree stays valid for the learner).
            self._completed = self._buf
            self.completed_initial_core = self._initial_core
            self._initial_core = core_before
            self._buf = self._carry_jit(self._completed)
            self._t = 1
            _M_UNROLLS.inc()
        else:
            self._t += 1
        _M_DISPATCH.observe(time.monotonic() - t0)
        return PendingAction(action), rng

    def take_unroll(self) -> Optional[dict]:
        """The completed ``[T+1, B, ...]`` device unroll, or None.  Reading
        clears it; ``completed_initial_core`` stays valid until the next
        unroll completes."""
        out, self._completed = self._completed, None
        return out


# --------------------------------------------------------------------------
# Anakin: env fused into the rollout (zero host-boundary bytes per frame)
# --------------------------------------------------------------------------

_ANAKIN_JIT_CACHE: Dict[Tuple, Tuple[Any, ...]] = {}


def _env_cache_key(env) -> Tuple:
    """JaxEnv instances are plain-attribute config objects; their identity
    for executable sharing is (class, config)."""
    return (
        type(env).__module__,
        type(env).__qualname__,
        tuple(sorted(vars(env).items())),
    )


def _build_anakin_jits(model, env, unroll_length: int):
    from .envs import jax_envs

    T = unroll_length

    def _body(params, carry):
        """One fused timestep: act on the carried observation, then step the
        batched env ON DEVICE (vmap), auto-reset included.  Identical math to
        ``DeviceRollout``'s ``_step`` — same split order, same f32 staging —
        so a JaxEnv rollout is bit-comparable between per-step and scan modes.
        """
        obs = carry["obs"]
        rng, act_rng = jax.random.split(carry["rng"])
        inputs = {
            "state": obs.astype(jnp.float32)[None],
            "reward": carry["reward"][None],
            "done": carry["done"][None],
            "prev_action": carry["prev_action"][None],
        }
        out, new_core = model.apply(
            params, inputs, carry["core"], sample_rng=act_rng
        )
        action = out["action"][0]
        row = {
            "state": obs,
            "reward": carry["reward"],
            "done": carry["done"],
            "prev_action": carry["prev_action"],
            "action": action,
            "policy_logits": out["policy_logits"][0],
        }
        env_state, ts = jax_envs.batch_step(env, carry["env"], action)
        # Device-side episode accounting: aggregates only ever leave the chip
        # through the explicit stats() snapshot, never per frame.
        st = carry["stats"]
        ep_return = st["ep_return"] + ts["reward"]
        ep_len = st["ep_len"] + 1
        d = ts["done"]
        stats = {
            "ep_return": jnp.where(d, 0.0, ep_return),
            "ep_len": jnp.where(d, 0, ep_len),
            "return_sum": st["return_sum"] + jnp.sum(jnp.where(d, ep_return, 0.0)),
            "len_sum": st["len_sum"] + jnp.sum(jnp.where(d, ep_len, 0)),
            "episodes": st["episodes"] + jnp.sum(d.astype(jnp.int32)),
        }
        new_carry = {
            "env": env_state,
            "obs": ts["state"],
            "reward": ts["reward"],
            "done": ts["done"],
            "prev_action": action,
            "core": new_core,
            "rng": rng,
            "stats": stats,
        }
        return new_carry, row

    def _step(params, buf, t, carry):
        carry, row = _body(params, carry)
        buf = {
            k: jax.lax.dynamic_update_slice_in_dim(buf[k], row[k][None], t, axis=0)
            for k in buf
        }
        return buf, carry

    def _carry_buf(buf):
        return {k: jnp.zeros_like(v).at[0].set(v[T]) for k, v in buf.items()}

    def _scan(params, carry, length):
        return jax.lax.scan(
            lambda c, _: _body(params, c), carry, None, length=length
        )

    def _finish(params, carry, rows_head):
        """Shared tail of both unroll entrypoints: run the last body step
        outside the scan so the core state ENTERING row T (= row 0 of the
        next unroll) is available as ``completed_initial_core`` for the
        learner without stacking cores across time."""
        core_into_last = carry["core"]
        carry, last = _body(params, carry)
        buf = jax.tree_util.tree_map(
            lambda *parts: jnp.concatenate(
                [p if p.ndim > parts[-1].ndim else p[None] for p in parts], axis=0
            ),
            *rows_head,
            last,
        )
        last_row = {k: buf[k][T] for k in buf}
        return buf, last_row, carry, core_into_last

    def _unroll_first(params, carry):
        # Bootstrap: no carried row yet, so rows 0..T-1 come from the scan
        # and row T from the explicit tail step — T+1 env steps, ONE dispatch.
        carry, rows = _scan(params, carry, T)
        return _finish(params, carry, (rows,))

    def _unroll_next(params, last_row, carry):
        # Steady state: row 0 is the carried last row of the previous unroll
        # (the reference carry-over), rows 1..T-1 from the scan, row T from
        # the tail step — T env steps, ONE dispatch.
        carry, rows = _scan(params, carry, T - 1)
        return _finish(params, carry, (last_row, rows))

    return (
        devmon.instrument_jit(jax.jit(_step, donate_argnums=(1,)), "anakin.step"),
        devmon.instrument_jit(jax.jit(_carry_buf), "anakin.carry"),
        devmon.instrument_jit(jax.jit(_unroll_first), "anakin.unroll_first"),
        devmon.instrument_jit(jax.jit(_unroll_next), "anakin.unroll_next"),
    )


class AnakinRollout:
    """Fully on-device rollout: jitted env + model, zero crossings per frame.

    Two modes over the same fused body (``tests/test_jax_envs.py`` proves
    them equivalent):

    - **per-step** (:meth:`step`): the fused env+act step writes timestep
      ``t`` into the donated ``[T+1, B]`` buffer — ``DeviceRollout``'s
      exact bookkeeping (carry row ``T`` to row 0, non-donated carry copy),
      with the env now inside the executable;
    - **scan** (:meth:`unroll`): one ``lax.scan`` dispatch produces the
      whole completed unroll.  This is the throughput path: per-frame
      dispatch cost disappears entirely, the host only enqueues one call
      per T steps.

    Neither mode touches ``actor_h2d/d2h_bytes_total``: observations,
    actions, and rewards are born and consumed on device.  Episode stats
    accumulate on device and leave only through :meth:`stats`
    (``actor_stats_d2h_bytes_total``).

    One instance is one mode: mixing :meth:`step` and :meth:`unroll` on the
    same instance would interleave two bookkeeping schemes over one env
    state and raises.
    """

    def __init__(self, model, env, batch_size: int, unroll_length: int, *,
                 env_key, act_rng, mesh=None, max_inflight: int = 2):
        from .envs import jax_envs

        self.batch_size = batch_size
        self.unroll_length = unroll_length
        self.env = env
        self.frames_done = 0
        # Scan-mode backpressure: unroll() is pure async dispatch, so an
        # unpaced caller (a host loop with nothing else to wait on — the
        # whole point of Anakin) would race arbitrarily far ahead of the
        # device, inflating dispatch-side step counts and ballooning the
        # execution queue.  Cap the dispatched-but-unfinished unrolls at
        # ``max_inflight`` (2 = classic double buffering: one computing,
        # one queued) by blocking on the oldest before dispatching past it.
        self._max_inflight = max(1, int(max_inflight))
        self._inflight: list = []
        obs_shape, obs_dtype = env.obs_spec
        cache_key = (model, _env_cache_key(env), batch_size, unroll_length)
        jits = _ANAKIN_JIT_CACHE.get(cache_key)
        if jits is None:
            jits = _ANAKIN_JIT_CACHE.setdefault(
                cache_key, _build_anakin_jits(model, env, unroll_length)
            )
        (self._step_jit, self._carry_jit,
         self._unroll_first_jit, self._unroll_next_jit) = jits

        B = batch_size
        env_state = jax_envs.batch_init(env, env_key, B)
        self._carry = {
            "env": env_state,
            "obs": jax_envs.batch_observe(env, env_state),
            # First reset: reward 0, done False — EnvPool's first-obs
            # convention, so backends line up from step 0.
            "reward": jnp.zeros((B,), jnp.float32),
            "done": jnp.zeros((B,), bool),
            "prev_action": jnp.zeros((B,), jnp.int32),
            "core": model.initial_state(B),
            "rng": act_rng,
            "stats": {
                "ep_return": jnp.zeros((B,), jnp.float32),
                "ep_len": jnp.zeros((B,), jnp.int32),
                "return_sum": jnp.zeros((), jnp.float32),
                "len_sum": jnp.zeros((), jnp.int32),
                "episodes": jnp.zeros((), jnp.int32),
            },
        }
        T1 = unroll_length + 1
        self._buf = {
            "state": jnp.zeros((T1, B, *obs_shape), obs_dtype),
            "reward": jnp.zeros((T1, B), jnp.float32),
            "done": jnp.zeros((T1, B), bool),
            "prev_action": jnp.zeros((T1, B), jnp.int32),
            "action": jnp.zeros((T1, B), jnp.int32),
            "policy_logits": jnp.zeros((T1, B, env.num_actions), jnp.float32),
        }
        if mesh is not None:
            # Sebulba: pin the whole rollout working set to the ACTOR submesh
            # (batch leaves sharded over its dp axis, scalars replicated on
            # it) — the jits then compile as SPMD programs over the actor
            # devices only, leaving the learner submesh free to overlap.
            from jax.sharding import NamedSharding, PartitionSpec as P

            dp = mesh.shape.get("dp", 1)
            if B % dp:
                raise ValueError(
                    f"actor-mesh dp={dp} must divide batch_size={B}"
                )
            batch_sh = NamedSharding(mesh, P("dp"))
            rep = NamedSharding(mesh, P())

            def _place(x):
                batched = getattr(x, "ndim", 0) >= 1 and x.shape[0] == B
                return jax.device_put(x, batch_sh if batched else rep)

            self._carry = jax.tree_util.tree_map(_place, self._carry)
            self._buf = jax.device_put(
                self._buf, NamedSharding(mesh, P(None, "dp"))
            )
        self._t = 0
        self._mode: Optional[str] = None
        self._last_row: Optional[dict] = None
        self._initial_core = self._carry["core"]
        self._completed: Optional[dict] = None
        self.completed_initial_core = None

    def _claim_mode(self, mode: str) -> None:
        if self._mode is None:
            self._mode = mode
        elif self._mode != mode:
            raise RuntimeError(
                f"AnakinRollout is in {self._mode!r} mode; one instance is "
                "one mode (per-step and scan bookkeeping share the env state)"
            )

    def step(self, params) -> None:
        """One fused env+act step into the donated buffer.  No arguments
        besides params and no return: there is nothing to upload and no
        action to fetch — the env that consumes the action is inside the
        same executable."""
        self._claim_mode("step")
        t0 = time.monotonic()
        core_before = self._carry["core"]
        self._buf, self._carry = self._step_jit(
            params, self._buf, self._t, self._carry
        )
        _M_FRAMES.inc(self.batch_size)
        self.frames_done += self.batch_size
        if self._t == self.unroll_length:
            self._completed = self._buf
            self.completed_initial_core = self._initial_core
            self._initial_core = core_before
            self._buf = self._carry_jit(self._completed)
            self._t = 1
            _M_UNROLLS.inc()
        else:
            self._t += 1
        _M_DISPATCH.observe(time.monotonic() - t0)

    def take_unroll(self) -> Optional[dict]:
        """Per-step mode hand-over: the completed device unroll, or None."""
        out, self._completed = self._completed, None
        return out

    def unroll(self, params) -> dict:
        """The scan fast path: ONE dispatch -> a completed ``[T+1, B]``
        device pytree.  Sets ``completed_initial_core`` to the core state
        entering the unroll's row 0, exactly as per-step mode does."""
        self._claim_mode("scan")
        t0 = time.monotonic()
        if self._last_row is None:
            buf, self._last_row, self._carry, next_initial = (
                self._unroll_first_jit(params, self._carry)
            )
            steps = self.unroll_length + 1
        else:
            buf, self._last_row, self._carry, next_initial = (
                self._unroll_next_jit(params, self._last_row, self._carry)
            )
            steps = self.unroll_length
        self.completed_initial_core = self._initial_core
        self._initial_core = next_initial
        # All leaves of one dispatch come from the same XLA execution, so
        # blocking on any one of them waits for the whole unroll.  Retire the
        # oldest dispatch once the window is full -- keeps dispatch-side
        # frame accounting within max_inflight unrolls of computed reality.
        self._inflight.append(buf["done"])
        while len(self._inflight) > self._max_inflight:
            # mtlint: allow-host-sync(max_inflight backpressure: deliberately retire the oldest dispatch so frame accounting cannot race the device)
            jax.block_until_ready(self._inflight.pop(0))
        _M_FRAMES.inc(self.batch_size * steps)
        self.frames_done += self.batch_size * steps
        _M_UNROLLS.inc()
        _M_DISPATCH.observe(time.monotonic() - t0)
        return buf

    def stats(self) -> Dict[str, Any]:
        """Snapshot the device-side episode aggregates (cumulative).  The
        ONLY D2H in the Anakin plane — counted on its own counter so the
        per-frame boundary reads a measured zero."""
        # mtlint: allow-host-sync(the documented sole D2H of the Anakin plane, counted on actor_stats_d2h_bytes_total)
        host = jax.device_get(self._carry["stats"])
        _M_STATS_D2H.inc(
            # mtlint: allow-host-sync(byte accounting over the already-fetched host snapshot)
            int(sum(np.asarray(v).nbytes for v in jax.tree_util.tree_leaves(host)))
        )
        return {
            "episodes": int(host["episodes"]),
            "return_sum": float(host["return_sum"]),
            "len_sum": int(host["len_sum"]),
            "ep_return": np.asarray(host["ep_return"]),  # mtlint: allow-host-sync(already-fetched host snapshot)
            "ep_len": np.asarray(host["ep_len"]),  # mtlint: allow-host-sync(already-fetched host snapshot)
        }
