"""Device-resident actor rollout buffers (the Podracer/Sebulba data plane).

The legacy actor path (``examples/vtrace/experiment.py`` host-batcher branch)
moves every observation across the host↔device boundary three times, in
float32: a host ``astype(np.float32)`` before upload (4x the H2D bytes of the
uint8 frame the env produced), a D2H when the host time-batcher stacks the
step back into an unroll, and a second H2D when the assembled learner batch
reaches the device.  On a colocated chip those are wasted DMAs; through a
dispatch tunnel they are the whole agent (VERDICT round 5: 74.9 env_frames/s
end-to-end vs 84k learner-only).

This module keeps the rollout on the device instead (arXiv:2104.06272 §
Sebulba: "rollouts are built in device memory"):

- one ``[T+1, B, ...]`` buffer pytree lives in device memory; the fused,
  jitted act step writes timestep ``t`` into it with
  ``jax.lax.dynamic_update_slice_in_dim`` and the buffer is **donated**, so
  XLA updates it in place instead of reallocating 6 arrays per step;
- the observation crosses the boundary **once, in its native dtype** (uint8
  frames stay uint8 — normalization is the model's on-chip ``astype/255``);
- the PRNG key is carried on-device through the fused step (the per-step
  ``jax.random.split`` host dispatch disappears; the split happens inside
  the same executable, producing bit-identical keys);
- the action comes back as a device array whose D2H transfer is started
  with ``copy_to_host_async()`` at dispatch time; :class:`PendingAction`
  realizes it as late as possible so ``EnvPool.step`` submission stops
  serializing behind a blocking ``np.asarray`` (dispatch is decoupled from
  fetch — the ``actor_act_dispatch_depth`` gauge counts in-flight actions,
  and realize time is accounted separately from dispatch time so the
  ``act`` timer stays honest under async dispatch);
- a completed unroll is handed over as a device pytree (consumed by the
  :class:`~moolib_tpu.batcher.Batcher` device-side path, which assembles
  learner batches by on-device cat/split — no further crossing), and the
  carried last timestep seeds the next buffer through a small **non**-donated
  jit, so the completed unroll stays valid while the fresh buffer is
  donated onwards (the donation-safety contract ``tests/test_rollout.py``
  locks down).

Bit-exactness: the fused step computes ``model.apply`` on the same float32
values the legacy path uploads (uint8 -> f32 is exact) and splits the key
with the same function, so device-rollout trajectories are bit-identical to
the legacy host-batcher path — ``tests/test_rollout.py`` compares
obs/actions/logits/core state with ``array_equal``.

Telemetry (docs/TELEMETRY.md): ``actor_h2d_bytes_total`` /
``actor_d2h_bytes_total`` / ``actor_frames_total`` make the one-crossing
contract a measured artifact (``benchmarks/agent_bench.py`` reports
``host_boundary_bytes_per_frame`` from them); ``actor_act_dispatch_seconds``
vs ``actor_act_realize_seconds`` split the old ``act`` wall time into its
dispatch and fetch halves.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry

_REG = telemetry.get_registry()
# Host-boundary accounting: every byte the actor path moves between host and
# device, by direction.  The legacy path increments these too (via
# count_h2d/count_d2h at its conversion sites), so the two rollout modes are
# comparable on one metric family.
_M_H2D = _REG.counter(
    "actor_h2d_bytes_total", "actor-path bytes uploaded host -> device"
)
_M_D2H = _REG.counter(
    "actor_d2h_bytes_total", "actor-path bytes fetched device -> host"
)
_M_FRAMES = _REG.counter(
    "actor_frames_total", "env frames through the actor path (for bytes/frame)"
)
_M_DISPATCH = _REG.histogram(
    "actor_act_dispatch_seconds", "act step dispatch (enqueue, not compute)"
)
_M_REALIZE = _REG.histogram(
    "actor_act_realize_seconds", "pending action realize (D2H completion wait)"
)
_M_DEPTH = _REG.gauge(
    "actor_act_dispatch_depth", "act steps dispatched but not yet realized"
)
_M_UNROLLS = _REG.counter("actor_unrolls_total", "completed [T+1, B] unrolls")


def count_h2d(nbytes: int) -> None:
    """Record an actor-path host->device crossing (legacy path call sites)."""
    _M_H2D.inc(nbytes)


def count_d2h(nbytes: int) -> None:
    """Record an actor-path device->host crossing (legacy path call sites)."""
    _M_D2H.inc(nbytes)


def count_frames(n: int) -> None:
    _M_FRAMES.inc(n)


class PendingAction:
    """A dispatched-but-not-realized action batch.

    Holds the device array with its ``copy_to_host_async()`` already issued;
    :meth:`realize` blocks only on whatever is still outstanding (ideally
    nothing — the transfer overlapped the host work since dispatch) and
    returns host numpy.  ``EnvPool.step`` also accepts the device array (or
    this object) directly; realizing explicitly keeps the fetch wait visible
    to the ``act_fetch`` timer/watchdog section instead of hiding it inside
    the env seam.
    """

    __slots__ = ("_dev", "_host")

    def __init__(self, action_dev):
        self._dev = action_dev
        self._host: Optional[np.ndarray] = None
        if hasattr(action_dev, "copy_to_host_async"):
            action_dev.copy_to_host_async()
        _M_DEPTH.inc()

    def realize(self) -> np.ndarray:
        if self._host is None:
            t0 = time.monotonic()
            self._host = np.asarray(self._dev)
            _M_REALIZE.observe(time.monotonic() - t0)
            _M_D2H.inc(self._host.nbytes)
            _M_DEPTH.dec()
        return self._host

    def __array__(self, dtype=None):
        out = self.realize()
        return out if dtype is None else out.astype(dtype, copy=False)

    @property
    def device_array(self):
        return self._dev


# One compiled (step, carry) pair per distinct rollout geometry: several
# actor batches of the same experiment share executables instead of
# compiling per DeviceRollout instance.  Keyed on the flax module (a frozen
# dataclass, hashable by config) + shapes/dtypes.
_JIT_CACHE: Dict[Tuple, Tuple[Any, Any]] = {}


def _build_jits(model, unroll_length: int):
    def _step(params, buf, t, state, reward, done, prev_action, core_state, rng):
        # Same split the legacy host loop performs per step — inside the
        # executable, so the key never leaves the device.
        rng, act_rng = jax.random.split(rng)
        inputs = {
            # On-chip normalization: uint8 -> f32 is exact, so the model sees
            # bit-identical values to the legacy host astype(np.float32).
            "state": state.astype(jnp.float32)[None],
            "reward": reward[None],
            "done": done[None],
            "prev_action": prev_action[None],
        }
        out, new_core = model.apply(params, inputs, core_state, sample_rng=act_rng)
        action = out["action"][0]
        logits = out["policy_logits"][0]
        row = {
            "state": state,  # native dtype: the buffer stores what the env sent
            "reward": reward,
            "done": done,
            "prev_action": prev_action,
            "action": action,
            "policy_logits": logits,
        }
        buf = {
            k: jax.lax.dynamic_update_slice_in_dim(buf[k], row[k][None], t, axis=0)
            for k in buf
        }
        return buf, action, new_core, rng

    def _carry(buf):
        # Seed the next unroll with the completed one's last timestep
        # (reference carry-over).  NOT donated: the completed buffer is the
        # learner's unroll and must outlive this copy.
        return {k: jnp.zeros_like(v).at[0].set(v[unroll_length]) for k, v in buf.items()}

    return (
        jax.jit(_step, donate_argnums=(1,)),
        jax.jit(_carry),
    )


class DeviceRollout:
    """Per-actor-batch device-resident rollout state.

    Drop-in replacement for the host-batcher bookkeeping in
    ``examples.common.EnvBatchState``: owns the ``[T+1, B, ...]`` device
    buffer, the carried LSTM core, the on-device previous action, and the
    unroll boundary logic (carry last step into the next buffer, track the
    initial core state entering each unroll).

    Usage per act step::

        pending, rng = roll.step(params, obs, rng)   # obs: EnvPool views
        ...                                          # overlap host work here
        env.step(batch, pending.realize())
        unroll = roll.take_unroll()                  # device pytree or None
        if unroll is not None:
            learn_batcher.cat(unroll)                # on-device assembly
            core_batcher.cat(roll.completed_initial_core)
    """

    def __init__(self, model, batch_size: int, unroll_length: int,
                 obs_shape: Tuple[int, ...], obs_dtype, num_actions: int):
        self.batch_size = batch_size
        self.unroll_length = unroll_length
        self._obs_dtype = np.dtype(obs_dtype)
        if self._obs_dtype == np.float64:
            # x64 is disabled on the device: stage f64 env vectors as f32 on
            # the host (same cast the legacy path makes) instead of letting
            # jit canonicalize a 2x-wide upload.
            self._obs_dtype = np.dtype(np.float32)
        key = (model, batch_size, unroll_length, tuple(obs_shape),
               self._obs_dtype.str, int(num_actions))
        jits = _JIT_CACHE.get(key)
        if jits is None:
            jits = _JIT_CACHE.setdefault(key, _build_jits(model, unroll_length))
        self._step_jit, self._carry_jit = jits
        T1 = unroll_length + 1
        B = batch_size
        self._buf = {
            "state": jnp.zeros((T1, B, *obs_shape), self._obs_dtype),
            "reward": jnp.zeros((T1, B), jnp.float32),
            "done": jnp.zeros((T1, B), bool),
            "prev_action": jnp.zeros((T1, B), jnp.int32),
            "action": jnp.zeros((T1, B), jnp.int32),
            "policy_logits": jnp.zeros((T1, B, num_actions), jnp.float32),
        }
        self._t = 0
        self.core_state = model.initial_state(batch_size)
        self.prev_action = jnp.zeros((B,), jnp.int32)
        # Initial LSTM state entering the unroll currently being filled.
        self._initial_core = self.core_state
        self._completed: Optional[dict] = None
        self.completed_initial_core = None

    def step(self, params, obs: Dict[str, np.ndarray], rng):
        """Upload one env observation batch (single crossing, native dtype),
        run the fused act step, and return ``(PendingAction, rng)``.

        ``rng`` is the carried device key; the split happens inside the
        executable.  The returned pending action's D2H is already issued.
        """
        t0 = time.monotonic()
        state = np.asarray(obs["state"])
        if state.dtype != self._obs_dtype:
            # Non-uint8 envs (e.g. float64 gym vectors): cast on host once to
            # the buffer dtype — still a single crossing.
            state = state.astype(self._obs_dtype)
        reward = np.asarray(obs["reward"], np.float32)
        done = np.asarray(obs["done"], bool)
        # THE crossing: the host arrays go straight into the fused call —
        # the jit C++ fastpath uploads them inline (native dtype, one DMA
        # per leaf), an order of magnitude cheaper per step than an
        # explicit python-side device_put.
        _M_H2D.inc(state.nbytes + reward.nbytes + done.nbytes)
        _M_FRAMES.inc(self.batch_size)
        core_before = self.core_state
        self._buf, action, self.core_state, rng = self._step_jit(
            params, self._buf, self._t, state, reward, done,
            self.prev_action, self.core_state, rng,
        )
        self.prev_action = action
        if self._t == self.unroll_length:
            # Index T written: the unroll is complete.  Hand it over and
            # seed the next buffer from its last step via the non-donated
            # carry (the completed pytree stays valid for the learner).
            self._completed = self._buf
            self.completed_initial_core = self._initial_core
            self._initial_core = core_before
            self._buf = self._carry_jit(self._completed)
            self._t = 1
            _M_UNROLLS.inc()
        else:
            self._t += 1
        _M_DISPATCH.observe(time.monotonic() - t0)
        return PendingAction(action), rng

    def take_unroll(self) -> Optional[dict]:
        """The completed ``[T+1, B, ...]`` device unroll, or None.  Reading
        clears it; ``completed_initial_core`` stays valid until the next
        unroll completes."""
        out, self._completed = self._completed, None
        return out
