"""Distributed two-level prioritized sampling over a replay shard cohort.

Level one runs on host, once per draw: every shard reports (size, priority
total) — local shards via the in-process seam, remote shards via their
``<name>.stats`` RPC — and the draw picks a shard proportionally to its
priority total with a seeded generator.  Level two runs on device inside
the chosen shard: the stratified sum-tree draw, corrected to the *cohort*
distribution by passing the cohort-wide N and priority total into the
sample jit (``P_global(i) = p_i / total_global``), so importance weights
are consistent with the two-level proportional scheme no matter which
shard served the batch.

Priority write-back routes by the sample's owning shard: device arrays go
straight back into a local shard's donated update, remote write-back is
fire-and-forget RPC (the learner never blocks on it).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ._metrics import REPLAY_FRAMES


class SampleRef(NamedTuple):
    """Routing handle for a sampled batch: which shard owns the slots."""

    shard: int
    indices: Any


class _LocalShard:
    def __init__(self, shard):
        self._shard = shard

    def stats(self):
        return {"size": len(self._shard), "total": self._shard.total_host()}

    def sample(self, batch_size, size_override, total_override):
        return self._shard.sample(
            batch_size,
            size_override=size_override,
            total_override=total_override,
        )

    def update(self, indices, priorities):
        self._shard.update_priorities(indices, priorities)


class _RemoteShard:
    def __init__(self, rpc, peer, name):
        self._rpc = rpc
        self._peer = peer
        self._name = name

    def stats(self):
        return self._rpc.sync(self._peer, f"{self._name}.stats")

    def sample(self, batch_size, size_override, total_override):
        out = self._rpc.sync(
            self._peer,
            f"{self._name}.dsample",
            batch_size,
            size_override,
            total_override,
        )
        return out["batch"], out["indices"], out["weights"]

    def update(self, indices, priorities):
        # The wire realizes the learner's device TD errors — the one
        # intentional crossing of the remote write-back path.
        indices = np.asarray(indices)  # mtlint: allow-host-sync(remote priority write-back crosses to the wire here, once per sampled batch)
        priorities = np.asarray(priorities)  # mtlint: allow-host-sync(remote priority write-back crosses to the wire here, once per sampled batch)
        self._rpc.async_(
            self._peer, f"{self._name}.update", indices, priorities
        )


class DistributedReplay:
    """Learner-side view over a cohort of replay shards.

    ``shards`` are in-process :class:`DeviceReplayShard` instances;
    ``remote_peers`` name peers serving a
    :class:`~moolib_tpu.replay.ingest.ReplayShardService` under the same
    ``name``.  API matches the single-shard store: ``sample`` returns
    ``(batch, SampleRef, weights)`` and ``update_priorities`` takes the
    ref back.
    """

    def __init__(
        self,
        shards: Sequence[Any] = (),
        rpc=None,
        remote_peers: Sequence[str] = (),
        name: str = "replay",
        seed: int = 0,
    ):
        self._shards: List[Any] = [_LocalShard(s) for s in shards]
        self._shards += [_RemoteShard(rpc, p, name) for p in remote_peers]
        if not self._shards:
            raise ValueError("DistributedReplay needs at least one shard")
        self._rng = np.random.default_rng(seed)

    def stats(self) -> List[dict]:
        """One (size, total) row per shard — the level-one refresh, one
        host round per draw (amortized over the whole batch)."""
        return [s.stats() for s in self._shards]

    def size(self) -> int:
        return sum(int(st["size"]) for st in self.stats())

    def sample(self, batch_size: int) -> Tuple[Any, SampleRef, Any]:
        stats = self.stats()
        totals = [float(st["total"]) for st in stats]
        global_n = sum(int(st["size"]) for st in stats)
        if global_n == 0:
            raise ValueError("replay cohort is empty")
        global_total = sum(totals)
        if global_total <= 0:
            probs = [1.0 / len(totals)] * len(totals)
        else:
            probs = [t / global_total for t in totals]
        pick = self._rng.choice(len(self._shards), p=probs)
        batch, idx, w = self._shards[pick].sample(
            batch_size, global_n, global_total
        )
        REPLAY_FRAMES.inc(batch_size, role="cohort_sample")
        return batch, SampleRef(int(pick), idx), w

    def update_priorities(self, ref: SampleRef, priorities) -> None:
        self._shards[ref.shard].update(ref.indices, priorities)
