"""Prioritized experience replay: host reference store + device-resident
distributed store.

- :mod:`~moolib_tpu.replay.host` — the original numpy/RPC store
  (``SumTree``/``ReplayBuffer``/``ReplayServer``/``ReplayClient``), kept
  as the compat shim and the bit-exactness reference.
- :mod:`~moolib_tpu.replay.device` — the sum-tree and ring storage as
  donated device arrays (``DeviceSumTree``/``DeviceReplayShard``).
- :mod:`~moolib_tpu.replay.ingest` — memfd-multicast trajectory publish
  and zero-copy shard adoption
  (``ReplayPublisher``/``ReplayShardService``).
- :mod:`~moolib_tpu.replay.distributed` — the two-level cohort draw
  (``DistributedReplay``/``SampleRef``).

Host names import eagerly (numpy only); the device-side names load
lazily so that importing the legacy store never pays the jax import.
"""

from .host import ReplayBuffer, ReplayClient, ReplayServer, SumTree, payload_bytes

_LAZY = {
    "DeviceSumTree": ("device", "DeviceSumTree"),
    "DeviceReplayShard": ("device", "DeviceReplayShard"),
    "ReplayPublisher": ("ingest", "ReplayPublisher"),
    "ReplayShardService": ("ingest", "ReplayShardService"),
    "DistributedReplay": ("distributed", "DistributedReplay"),
    "SampleRef": ("distributed", "SampleRef"),
}

__all__ = [
    "ReplayBuffer",
    "ReplayClient",
    "ReplayServer",
    "SumTree",
    "payload_bytes",
    *_LAZY,
]


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f".{entry[0]}", __name__)
    value = getattr(mod, entry[1])
    globals()[name] = value
    return value
