"""Zero-copy trajectory ingest: memfd-multicast publish, shard adoption.

Actors publish trajectory batches through the PR-4 multicast seam
(:meth:`Rpc.async_broadcast`): the payload serializes once and — when every
target shard is a same-host fd-passing peer — is written into a single
memfd mapped by all of them, so trajectory bytes leave the publishing
process exactly once per host, not once per consumer.  The write-once
invariant is measured right here: ``replay_bytes_total{direction=
"ingest_out"}`` increments once per publish with the payload size,
independent of the consumer count.

On the receiving side each shard's ``<name>.ingest`` handler runs inline:
its arguments are zero-copy read-only views over the delivered frame.  The
handler takes its round-robin stripe of the items, adopts the memfd
mapping (:func:`rpc.core.adopt_current_frame`) so the pages outlive the
handler, and queues the stripe; :meth:`ReplayShardService.drain` later
device_puts straight from the borrowed views into the device ring — one
host->device copy, zero host->host copies.  Frames that arrived over a
copying transport (TCP, small frames) are copied once in the handler
instead, since their receive buffer is recycled on return.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

from ..rpc import Rpc
from ..rpc import core as rpc_core
from ..utils import nest
from ._metrics import REPLAY_BYTES, REPLAY_FRAMES
from .host import _own_copy, payload_bytes


class ReplayPublisher:
    """Actor-side handle multicasting trajectory batches to a shard set."""

    def __init__(self, rpc: Rpc, shard_peers: Sequence[str], name: str = "replay"):
        self._rpc = rpc
        self._peers = list(shard_peers)
        self._name = name

    def multicast_ready(self) -> bool:
        """True when a publish will take the write-once memfd path (every
        shard reachable over a live same-host fd-passing connection)."""
        return self._rpc.multicast_ready(self._peers)

    def publish(self, items: Sequence[Any], priorities=None):
        """Broadcast one trajectory batch to every shard; returns the
        broadcast future (resolves once every shard has ingested)."""
        REPLAY_BYTES.inc(
            payload_bytes(items) + payload_bytes(priorities),
            direction="ingest_out",
        )
        REPLAY_FRAMES.inc(len(items), role="publish")
        return self._rpc.async_broadcast(
            self._peers, f"{self._name}.ingest", items, priorities
        )


class ReplayShardService:
    """Serve one :class:`DeviceReplayShard` to the cohort.

    Endpoints: ``<name>.ingest`` (inline, zero-copy), ``<name>.stats``
    (size + priority total for the across-shard draw), ``<name>.dsample``
    (cohort-corrected sample), ``<name>.update`` (priority write-back),
    ``<name>.size``.

    Handlers run on two kinds of thread — ``stats``/``dsample``/``size``
    on the Rpc worker pool (each drains pending stripes first), the inline
    ``ingest``/``update`` on the transport IO thread — and may overlap
    freely: the service lock only guards the pending-stripe queue, while
    the :class:`~moolib_tpu.replay.device.DeviceReplayShard` serializes
    its own donated add/sample/update under its per-shard mutex.
    """

    def __init__(
        self,
        rpc: Rpc,
        name: str,
        shard,
        shard_index: int = 0,
        num_shards: int = 1,
    ):
        self._rpc = rpc
        self._name = name
        self._shard = shard
        self._shard_index = int(shard_index)
        self._num_shards = int(num_shards)
        self._pending: List = []
        self._lock = threading.Lock()
        rpc.define(f"{name}.ingest", self._on_ingest, inline=True)
        rpc.define(f"{name}.stats", self._on_stats)
        rpc.define(f"{name}.dsample", self._on_sample)
        rpc.define(f"{name}.update", self._on_update, inline=True)
        rpc.define(f"{name}.size", self._on_size)

    # -- ingest (inline: runs on the transport IO thread) --------------------

    def _on_ingest(self, items, priorities=None):
        stripe = list(items[self._shard_index :: self._num_shards])
        prios = (
            None
            if priorities is None
            else priorities[self._shard_index :: self._num_shards]
        )
        # Adopt the memfd mapping: the borrowed views point into its pages,
        # which now stay alive until drain() has device_put them.
        owner = rpc_core.adopt_current_frame()
        if owner is None:
            # Copying transport — the receive buffer dies on return.
            stripe = [_own_copy(it) for it in stripe]
            prios = None if prios is None else list(prios)
        REPLAY_BYTES.inc(payload_bytes(stripe), direction="ingest_in")
        REPLAY_FRAMES.inc(len(stripe), role="ingest")
        with self._lock:
            self._pending.append((stripe, prios, owner))
        return len(stripe)

    def drain(self) -> int:
        """Insert queued stripes into the device ring — this is where the
        single host->device copy per trajectory happens.  Safe from any
        thread (the shard's own mutex serializes the donated inserts
        against concurrent sample/update).  Returns the number of items
        inserted.

        The ring insert is fixed-shape: the shard latches its insert width
        on the first ``add`` and pads shorter batches, so stripes wider
        than the latched width (publishers with varying batch sizes, a
        first partial publish) are SPLIT into latched-width chunks here
        rather than surfacing a width error inside an RPC handler."""
        with self._lock:
            pending, self._pending = self._pending, []
        inserted = 0
        for stripe, prios, _owner in pending:
            if not stripe:
                continue
            width = getattr(self._shard, "insert_width", None)
            if width is None:
                width = len(stripe)  # first insert latches the shard width
            for off in range(0, len(stripe), width):
                chunk = stripe[off : off + width]
                self._shard.add(
                    chunk,
                    None if prios is None else prios[off : off + width],
                )
                inserted += len(chunk)
        # _owner mappings drop here: pages were consumed by device_put.
        return inserted

    # -- cohort sampling seams ----------------------------------------------

    def _on_stats(self):
        self.drain()
        return {
            "size": len(self._shard),
            "total": self._shard.total_host(),
        }

    def _on_sample(self, batch_size, size_override=0, total_override=0.0):
        self.drain()
        batch, idx, w = self._shard.sample(
            batch_size, size_override=size_override, total_override=total_override
        )
        return {"batch": batch, "indices": idx, "weights": w}

    def _on_update(self, indices, priorities):
        self._shard.update_priorities(indices, priorities)
        return True

    def _on_size(self):
        self.drain()
        return len(self._shard)
