"""Replay data-plane metrics — one definition point for every series the
host and device stores share, so the registry sees a single consistent
registration regardless of which layer imports first."""

from __future__ import annotations

from ..telemetry import metrics

_REG = metrics.get_registry()

#: payload bytes crossing a replay seam, by direction:
#: ``add_in``/``sample_out`` (legacy host RPC store), ``ingest_out`` (one
#: increment per publish — NOT per consumer: the write-once invariant the
#: memfd multicast buys is measurable right here), ``ingest_in`` (per-shard
#: stripe adopted from the borrowed view).
REPLAY_BYTES = _REG.counter(
    "replay_bytes_total",
    "payload bytes crossing a replay seam (publish counted once per host, "
    "not per consumer)",
    ("direction",),
)

REPLAY_FRAMES = _REG.counter(
    "replay_frames_total",
    "trajectory items through the replay plane, by role "
    "(publish/ingest/insert/sample)",
    ("role",),
)

REPLAY_SAMPLE_SECONDS = _REG.histogram(
    "replay_sample_seconds",
    "wall time of one prioritized sample draw (dispatch-inclusive; the "
    "device path returns un-realized device arrays)",
)

REPLAY_PRIORITY_ROUNDS = _REG.counter(
    "replay_priority_update_rounds_total",
    "priority write-back rounds applied to a replay store",
)

REPLAY_OCCUPANCY = _REG.gauge(
    "replay_shard_occupancy",
    "items currently held by the local replay shard",
    ("shard",),
)
