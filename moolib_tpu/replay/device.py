"""Device-resident prioritized replay: ring storage and sum-tree as jnp arrays.

The host store (:mod:`moolib_tpu.replay.host`) keeps items as python lists
and walks a numpy sum-tree under a lock — every add/sample crosses the
host boundary and restacks the batch.  Here the whole store lives on
device:

- :class:`DeviceSumTree` — the sum-tree is one ``[2*capacity]`` device
  array (same layout as the numpy reference: root at 1, leaves at
  ``[capacity, 2*capacity)``).  ``set`` scatters leaf values and rebuilds
  the internal levels with one pairwise reduction per level — the same
  pairwise additions the reference's touched-path walk performs, so the
  tree is bit-exact vs ``host.SumTree`` at equal dtype.  ``sample``
  descends all targets in lockstep with a fixed trip count.
- :class:`DeviceReplayShard` — a ``[capacity, ...]`` donated device ring
  per pytree leaf.  Inserts are fixed-width masked scatters (lane padding
  + out-of-bounds drop), so slot churn never changes an abstract
  signature: every hot path is wrapped in devmon ``instrument_jit`` and
  compiles exactly once.  Sampling is a stratified proportional draw under
  the counter-based seeding contract (keys derived by ``fold_in`` on a
  draw counter) returning device pytrees straight into the learner's
  donated batch path; priority write-back accepts device arrays without
  realizing them.

The priority transform ``p -> max(p, 1e-6)**alpha`` is its own tiny jit
(:attr:`DeviceReplayShard.priority_transform`) shared by insert and
update — tests and the bench feed the *same compiled function* to the
numpy reference, which is what makes the bit-exactness comparison exact
rather than tolerance-based.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import devmon
from ..utils import nest
from ._metrics import (
    REPLAY_FRAMES,
    REPLAY_OCCUPANCY,
    REPLAY_PRIORITY_ROUNDS,
    REPLAY_SAMPLE_SECONDS,
)

_INSTANCE_SEQ = itertools.count()


def _pow2(n: int) -> int:
    cap = 1
    while cap < n:
        cap *= 2
    return cap


def _tree_from_leaves(leaves):
    """Assemble the full ``[2*cap]`` tree from its ``[cap]`` leaf level by
    pairwise level sums (index 0 stays zero, root lands at index 1)."""
    levels = [leaves]
    while levels[-1].shape[0] > 1:
        cur = levels[-1]
        levels.append(cur[0::2] + cur[1::2])
    parts = [jnp.zeros((1,), leaves.dtype)] + levels[::-1]
    return jnp.concatenate(parts)


def _descend(tree, targets, capacity: int):
    """Lockstep sum-tree descent: leaf index whose prefix-sum interval
    contains each target.  ``capacity`` is static, so the trip count is
    fixed at trace time."""
    t = targets.astype(tree.dtype)
    idx = jnp.ones(t.shape, jnp.int32)
    for _ in range(capacity.bit_length() - 1):
        left = tree[2 * idx]
        go_right = t > left
        t = jnp.where(go_right, t - left, t)
        idx = 2 * idx + go_right.astype(jnp.int32)
    return idx - capacity


class DeviceSumTree:
    """Sum-tree as a device array with jitted set/get/sample.

    Maskable: lanes whose index is ``>= capacity`` (after padding) are
    dropped by the scatter, so callers keep a fixed batch width and pad.
    Duplicate indices within one ``set`` batch write in unspecified order —
    callers pass distinct slots (ring inserts do by construction).
    """

    def __init__(self, capacity: int, dtype=jnp.float32, name: str = "replay_tree"):
        self.capacity = _pow2(capacity)
        self.dtype = jnp.dtype(dtype)
        self.tree = jnp.zeros(2 * self.capacity, self.dtype)
        cap = self.capacity
        tag = f"{name}[{next(_INSTANCE_SEQ)}]"

        def _set(tree, idx, value):
            leaves = tree[cap:].at[idx].set(value.astype(tree.dtype), mode="drop")
            return _tree_from_leaves(leaves)

        def _get(tree, idx):
            return tree[cap + idx]

        def _sample(tree, targets):
            return _descend(tree, targets, cap)

        self._set = devmon.instrument_jit(
            jax.jit(_set, donate_argnums=0), f"{tag}.set"
        )
        self._get = devmon.instrument_jit(jax.jit(_get), f"{tag}.get")
        self._sample = devmon.instrument_jit(jax.jit(_sample), f"{tag}.sample")

    def set(self, idx, value) -> None:
        self.tree = self._set(self.tree, jnp.asarray(idx), jnp.asarray(value))

    def total(self):
        """Root of the tree as an un-realized device scalar."""
        return self.tree[1]

    def get(self, idx):
        return self._get(self.tree, jnp.asarray(idx))

    def sample(self, targets):
        """Leaf indices for prefix-sum targets (device array in, device
        array out; the descent never touches the host)."""
        return self._sample(self.tree, jnp.asarray(targets))


def _stack_rows(items: Sequence[Any]):
    """Stack a list of item pytrees into one batch pytree.  Host (numpy)
    leaves batch with np.stack — including borrowed read-only ingest views,
    which this is the single copy of — so the ring insert pays exactly one
    host->device transfer per leaf; device leaves stack on device."""
    return nest.map_many(
        lambda *xs: np.stack(xs)
        if isinstance(xs[0], np.ndarray)
        else jnp.stack(xs),
        *items,
    )


def _pad_rows(batch, width: int, n: int):
    """Pad the leading (lane) axis out to the latched insert width."""
    if n == width:
        return batch

    def pad(x):
        if isinstance(x, np.ndarray):
            return np.concatenate(
                [x, np.zeros((width - n,) + x.shape[1:], x.dtype)]
            )
        return jnp.concatenate(
            [x, jnp.zeros((width - n,) + x.shape[1:], x.dtype)]
        )

    return nest.map(pad, batch)


class DeviceReplayShard:
    """One host's shard of the distributed device-resident replay store.

    API-compatible with :class:`moolib_tpu.replay.host.ReplayBuffer`
    (``add`` / ``sample`` / ``update_priorities`` / ``size``), except that
    ``sample`` returns *device* arrays and ``update_priorities`` accepts
    them — the learner's TD errors never visit the host.

    Thread-safe: every mutation donates ``self.tree`` (and the store) into
    a jit, so a reentrant mutex serializes add/sample/update and the
    realized reads — :class:`~moolib_tpu.replay.ingest.ReplayShardService`
    calls in from the Rpc worker pool *and* the transport IO thread
    (inline priority write-back), and a use-after-donate between them
    would corrupt the sum-tree.
    """

    def __init__(
        self,
        capacity: int,
        alpha: float = 0.6,
        beta: float = 0.4,
        seed: int = 0,
        name: str = "replay_shard",
        dtype=jnp.float32,
    ):
        self.capacity = int(capacity)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self._treecap = _pow2(self.capacity)
        self.dtype = jnp.dtype(dtype)
        self._tag = f"{name}[{next(_INSTANCE_SEQ)}]"
        self.tree = jnp.zeros(2 * self._treecap, self.dtype)
        self._store = None  # [capacity, ...] ring pytree, built on first add
        self._next = 0  # host-side ring cursor (bookkeeping ints, no sync)
        self._size = 0
        self._maxp = jnp.ones((), self.dtype)  # running max RAW priority
        self._base_key = jax.random.key(seed)
        self._draws = 0  # fold_in counter: the seeding contract's epoch
        self._ins_width: Optional[int] = None
        self._upd_width: Optional[int] = None
        self._sample_jits = {}
        self._transform_jits = {}
        self._lock = threading.RLock()

        def _default_fill(maxp, width: int):
            return jnp.broadcast_to(maxp, (width,))

        self._default_fill = devmon.instrument_jit(
            jax.jit(_default_fill, static_argnums=1), f"{self._tag}.fill"
        )

    def priority_transform(self, p):
        """The one alpha-pow ``max(p, 1e-6)**alpha`` used for every leaf
        value that enters the tree (insert and update) — the bit-exactness
        tests run the numpy reference through this same compiled fn.  One
        instrumented jit per batch width, so fixed-width callers never
        register a second signature on a devmon name."""
        p = jnp.asarray(p)
        width = int(p.shape[0])
        fn = self._transform_jits.get(width)
        if fn is None:
            dt = self.dtype
            alpha = self.alpha

            def _transform(p):
                return jnp.maximum(p.astype(dt), 1e-6) ** jnp.asarray(alpha, dt)

            fn = self._transform_jits[width] = devmon.instrument_jit(
                jax.jit(_transform), f"{self._tag}.transform[{width}]"
            )
        return fn(p)

    def __len__(self) -> int:
        return self._size

    def size(self) -> int:
        return self._size

    # -- insert ------------------------------------------------------------

    def _build_insert(self, width: int):
        capacity, treecap = self.capacity, self._treecap

        def _insert(store, tree, maxp, batch, praw, p_alpha, start, count):
            lanes = jnp.arange(width, dtype=jnp.int32)
            valid = lanes < count
            slots = (start + lanes) % capacity
            # Out-of-bounds sentinel lanes are dropped by the scatter, so a
            # short batch keeps the same abstract signature as a full one.
            store_slots = jnp.where(valid, slots, capacity)
            tree_slots = jnp.where(valid, slots, treecap)
            store = nest.map_many(
                lambda s, b: s.at[store_slots].set(
                    b.astype(s.dtype), mode="drop"
                ),
                store,
                batch,
            )
            leaves = tree[treecap:].at[tree_slots].set(
                p_alpha.astype(tree.dtype), mode="drop"
            )
            tree = _tree_from_leaves(leaves)
            maxp = jnp.maximum(
                maxp, jnp.max(jnp.where(valid, praw.astype(maxp.dtype), 0))
            )
            return store, tree, maxp

        return devmon.instrument_jit(
            jax.jit(_insert, donate_argnums=(0, 1, 2)),
            f"{self._tag}.insert",
        )

    @property
    def insert_width(self) -> Optional[int]:
        """The latched fixed insert width (None until the first ``add``) —
        ingest callers split larger stripes to this before inserting."""
        return self._ins_width

    def add(self, items: Sequence[Any], priorities=None):
        """Insert a fixed-width batch of item pytrees; returns slot indices
        (host ints — ring bookkeeping, not a device readback)."""
        with self._lock:
            n = len(items)
            if self._ins_width is None:
                self._ins_width = n
                self._insert = self._build_insert(n)
            elif n > self._ins_width:
                raise ValueError(
                    f"insert width grew {self._ins_width} -> {n}: the ring "
                    "insert is fixed-shape (pad or split the batch)"
                )
            width = self._ins_width
            batch = _pad_rows(_stack_rows(items), width, n)
            if self._store is None:
                self._store = nest.map(
                    lambda b: jnp.zeros(
                        (self.capacity,) + tuple(b.shape[1:]), b.dtype
                    ),
                    batch,
                )
            if priorities is None:
                praw = self._default_fill(self._maxp, width)
            else:
                praw = np.zeros(width, np.float32)
                praw[:n] = priorities
            p_alpha = self.priority_transform(praw)
            self._store, self.tree, self._maxp = self._insert(
                self._store,
                self.tree,
                self._maxp,
                batch,
                praw,
                p_alpha,
                np.int32(self._next),
                np.int32(n),
            )
            idxs = [(self._next + i) % self.capacity for i in range(n)]
            self._next = (self._next + n) % self.capacity
            self._size = min(self._size + n, self.capacity)
        REPLAY_FRAMES.inc(n, role="insert")
        REPLAY_OCCUPANCY.set(self._size, shard=self._tag)
        return idxs

    # -- sample ------------------------------------------------------------

    def _build_sample(self, batch_size: int):
        treecap, beta = self._treecap, self.beta

        def _sample(store, tree, key, size, n_div, total_div):
            dt = tree.dtype
            total = tree[1]
            u = jax.random.uniform(key, (batch_size,), dt)
            seg = total / batch_size
            targets = (jnp.arange(batch_size, dtype=dt) + u) * seg
            # Largest representable value strictly below total in the
            # tree's own dtype (1 - 1e-9 rounds to exactly 1.0 in f32).
            targets = jnp.minimum(targets, total * (1 - jnp.finfo(dt).epsneg))
            idx = _descend(tree, targets, treecap)
            # The clip guards never-written zero-priority slots, so it is
            # always against the LOCAL occupancy — the ring only holds
            # ``size`` items regardless of the cohort-wide count.
            idx = jnp.clip(idx, 0, jnp.maximum(size - 1, 0))
            # Global correction: in the distributed draw, probs divide by
            # the cohort-wide total and N is the cohort-wide size, so
            # weights are globally consistent; 0 means "local".
            eff_total = jnp.where(total_div > 0, total_div, total)
            eff_n = jnp.where(n_div > 0, n_div, size)
            probs = tree[treecap + idx] / jnp.maximum(eff_total, 1e-12)
            w = (eff_n.astype(dt) * jnp.maximum(probs, 1e-12)) ** (-beta)
            w = w / jnp.max(w)
            batch = nest.map(lambda leaf: leaf[idx], store)
            return batch, idx, w

        return devmon.instrument_jit(jax.jit(_sample), f"{self._tag}.sample")

    def sample(self, batch_size: int, size_override: int = 0, total_override: float = 0.0):
        """(device batch pytree, device indices, device weights).

        ``size_override``/``total_override`` are the cohort-wide N and
        priority total for the distributed two-level draw (they only
        rescale the importance weights — indices always stay within the
        local ring); 0 keeps the shard-local correction.
        """
        with self._lock:
            if self._size == 0 or self._store is None:
                raise ValueError("replay shard is empty")
            fn = self._sample_jits.get(batch_size)
            if fn is None:
                fn = self._sample_jits[batch_size] = self._build_sample(
                    batch_size
                )
            key = jax.random.fold_in(self._base_key, self._draws)
            self._draws += 1
            with REPLAY_SAMPLE_SECONDS.time():
                batch, idx, w = fn(
                    self._store,
                    self.tree,
                    key,
                    np.int32(self._size),
                    np.int32(size_override),
                    np.float32(total_override),
                )
        REPLAY_FRAMES.inc(batch_size, role="sample")
        return batch, idx, w

    # -- priority write-back ------------------------------------------------

    def _build_update(self, width: int):
        treecap = self._treecap

        def _update(tree, maxp, idx, praw, p_alpha, count):
            lanes = jnp.arange(width, dtype=jnp.int32)
            valid = lanes < count
            tree_slots = jnp.where(valid, idx.astype(jnp.int32), treecap)
            # Stratified draws return duplicate indices routinely; the
            # scatter's duplicate order is unspecified in JAX, so mask all
            # but the LAST occurrence of each slot — the numpy reference's
            # ``tree[pos] = value`` is deterministically last-wins.
            dup_later = (tree_slots[None, :] == tree_slots[:, None]) & (
                lanes[None, :] > lanes[:, None]
            )
            tree_slots = jnp.where(
                jnp.any(dup_later, axis=1), treecap, tree_slots
            )
            leaves = tree[treecap:].at[tree_slots].set(
                p_alpha.astype(tree.dtype), mode="drop"
            )
            tree = _tree_from_leaves(leaves)
            maxp = jnp.maximum(
                maxp, jnp.max(jnp.where(valid, praw.astype(maxp.dtype), 0))
            )
            return tree, maxp

        return devmon.instrument_jit(
            jax.jit(_update, donate_argnums=(0, 1)), f"{self._tag}.update"
        )

    def update_priorities(self, indices, priorities) -> None:
        """Write back new priorities (device or host arrays — device TD
        errors are consumed without realizing them on host).  Duplicate
        indices resolve last-wins, matching the numpy reference."""
        with self._lock:
            indices = jnp.asarray(indices)
            n = int(indices.shape[0])
            if self._upd_width is None:
                self._upd_width = n
                self._update = self._build_update(n)
            elif n > self._upd_width:
                raise ValueError(
                    f"priority-update width grew {self._upd_width} -> {n}: "
                    "fixed-shape contract (pad or split the batch)"
                )
            width = self._upd_width
            praw = jnp.asarray(priorities, self.dtype)
            if n < width:
                indices = jnp.concatenate(
                    [indices, jnp.zeros(width - n, indices.dtype)]
                )
                praw = jnp.concatenate(
                    [praw, jnp.zeros(width - n, praw.dtype)]
                )
            p_alpha = self.priority_transform(praw)
            self.tree, self._maxp = self._update(
                self.tree, self._maxp, indices, praw, p_alpha, np.int32(n)
            )
        REPLAY_PRIORITY_ROUNDS.inc()

    # -- cohort seams --------------------------------------------------------

    def total(self):
        """Priority-sum root as an un-realized device scalar."""
        with self._lock:
            return self.tree[1]

    def total_host(self) -> float:
        """Realized priority total — the intentional host seam the
        across-shard proportional allocation reads once per draw round
        (amortized over a whole sampled batch, not per frame)."""
        with self._lock:
            total = self.tree[1]
        return float(total)

    def leaf_priorities(self):
        """The ``[capacity]`` transformed-priority leaf level as a device
        array (tests compare it against the numpy reference)."""
        with self._lock:
            return self.tree[self._treecap : self._treecap + self.capacity]
