"""Host-side prioritized replay: the bit-exactness reference and RPC store.

This is the original ``moolib_tpu/replay.py`` (seed lineage), kept as the
compat shim and as the numpy reference the device store
(:mod:`moolib_tpu.replay.device`) is verified bit-exact against:

- :class:`SumTree` — numpy sum-tree, O(log n) vectorized updates.  The
  ``dtype`` parameter (default float64, the historical behavior) lets tests
  run the reference in float32, the device store's dtype, so comparisons
  are exact rather than tolerance-based.
- :class:`ReplayBuffer` — in-memory prioritized buffer (proportional
  sampling, PER importance weights), thread-safe, pytree items.
- :class:`ReplayServer` — add/sample/update_priorities/size over RPC.
  Handlers are registered ``inline=True``: arguments arrive as zero-copy
  read-only views over the receive buffer (``deserialize(borrow=True)``),
  and the store copies each payload exactly once into buffer-owned memory
  instead of the old pickle-copy-then-store double copy.  Payload traffic
  is counted on ``replay_bytes_total{direction}``.
- :class:`ReplayClient` — call-through wrappers returning RPC futures.

Sampling returns (batch, indices, importance weights) with the standard
PER correction ``w_i = (N * P(i))^-beta / max_j w_j``.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..rpc import Rpc
from ..utils import nest
from ._metrics import REPLAY_BYTES


def payload_bytes(tree: Any) -> int:
    """Total array bytes in a pytree (non-array leaves count as zero)."""
    total = 0
    for leaf in nest.flatten(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def _own_copy(tree: Any) -> Any:
    """Copy borrowed array views into owned memory (one copy, at the only
    place the store retains data past the inline handler's return)."""
    return nest.map(
        lambda x: np.array(x, copy=True) if isinstance(x, np.ndarray) else x,
        tree,
    )


class SumTree:
    """Binary indexed sum-tree over fixed capacity (power of two internally)."""

    def __init__(self, capacity: int, dtype=np.float64):
        self.capacity = 1
        while self.capacity < capacity:
            self.capacity *= 2
        self.dtype = np.dtype(dtype)
        self.tree = np.zeros(2 * self.capacity, dtype=self.dtype)

    def set(self, idx, value) -> None:
        idx = np.atleast_1d(np.asarray(idx, np.int64))
        value = np.atleast_1d(np.asarray(value, self.dtype))
        pos = idx + self.capacity
        self.tree[pos] = value
        # Walk the touched paths up, one vectorized level at a time.
        parents = np.unique(pos // 2)
        while parents[0] >= 1:
            self.tree[parents] = self.tree[2 * parents] + self.tree[2 * parents + 1]
            if parents[0] == 1:
                break
            parents = np.unique(parents // 2)

    def total(self) -> float:
        return float(self.tree[1])

    def get(self, idx) -> np.ndarray:
        return self.tree[np.asarray(idx, np.int64) + self.capacity]

    def sample(self, targets: np.ndarray) -> np.ndarray:
        """Find leaf indices whose prefix-sum interval contains each target."""
        idx = np.ones(len(targets), dtype=np.int64)
        t = np.asarray(targets, self.dtype).copy()
        while idx[0] < self.capacity:
            left = self.tree[2 * idx]
            go_right = t > left
            t = np.where(go_right, t - left, t)
            idx = 2 * idx + go_right
        return idx - self.capacity


class ReplayBuffer:
    """Prioritized ring buffer of pytree items."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4, seed=None):
        self.capacity = int(capacity)
        self.alpha = alpha
        self.beta = beta
        self._tree = SumTree(self.capacity)
        self._items: List[Any] = [None] * self.capacity
        self._next = 0
        self._size = 0
        self._max_priority = 1.0
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def size(self) -> int:
        return self._size

    def add(self, items: Sequence[Any], priorities: Optional[Sequence[float]] = None):
        """Insert items (list of pytrees); returns their slot indices."""
        with self._lock:
            n = len(items)
            if priorities is None:
                priorities = [self._max_priority] * n
            idxs = [(self._next + i) % self.capacity for i in range(n)]
            for i, item in zip(idxs, items):
                self._items[i] = item
            prios = np.maximum(np.asarray(priorities, np.float64), 1e-6)
            self._max_priority = max(self._max_priority, float(prios.max()))
            self._tree.set(np.asarray(idxs), prios**self.alpha)
            self._next = (self._next + n) % self.capacity
            self._size = min(self._size + n, self.capacity)
            return idxs

    def sample(self, batch_size: int) -> Tuple[Any, np.ndarray, np.ndarray]:
        """(stacked batch, indices, importance weights)."""
        with self._lock:
            if self._size == 0:
                raise ValueError("replay buffer is empty")
            total = self._tree.total()
            # Stratified proportional sampling.
            seg = total / batch_size
            targets = (np.arange(batch_size) + self._rng.random(batch_size)) * seg
            idxs = self._tree.sample(np.minimum(targets, total * (1 - 1e-9)))
            # Guard slots never written (tree zero-padded region).
            idxs = np.clip(idxs, 0, max(self._size - 1, 0))
            probs = self._tree.get(idxs) / max(total, 1e-12)
            weights = (self._size * np.maximum(probs, 1e-12)) ** (-self.beta)
            weights = weights / weights.max()
            batch = nest.stack([self._items[int(i)] for i in idxs], dim=0)
            return batch, idxs.astype(np.int64), weights.astype(np.float32)

    def update_priorities(self, indices, priorities) -> None:
        with self._lock:
            prios = np.maximum(np.asarray(priorities, np.float64), 1e-6)
            self._max_priority = max(self._max_priority, float(prios.max()))
            self._tree.set(np.asarray(indices, np.int64), prios**self.alpha)


class ReplayServer:
    """Serve a ReplayBuffer to the cohort over RPC.

    All handlers run ``inline=True``: the add/update payloads arrive as
    borrowed zero-copy views over the receive buffer, and ``_on_add`` copies
    them exactly once into buffer-owned memory (the buffer outlives the
    frame).  The handlers only take the buffer's own short-lived lock, so
    they are safe on the transport's IO thread.
    """

    def __init__(self, rpc: Rpc, name: str, buffer: ReplayBuffer):
        self._rpc = rpc
        self._buffer = buffer
        self._name = name
        rpc.define(f"{name}.add", self._on_add, inline=True)
        rpc.define(f"{name}.sample", self._on_sample, inline=True)
        rpc.define(f"{name}.update_priorities", self._on_update, inline=True)
        rpc.define(f"{name}.size", self._buffer.size)

    def _on_add(self, items, priorities=None):
        REPLAY_BYTES.inc(payload_bytes(items), direction="add_in")
        items = [_own_copy(it) for it in items]
        if priorities is not None:
            priorities = np.array(priorities, copy=True)
        return self._buffer.add(items, priorities)

    def _on_sample(self, batch_size):
        batch, idxs, weights = self._buffer.sample(batch_size)
        REPLAY_BYTES.inc(payload_bytes(batch), direction="sample_out")
        return {"batch": batch, "indices": idxs, "weights": weights}

    def _on_update(self, indices, priorities):
        self._buffer.update_priorities(indices, priorities)
        return True


class ReplayClient:
    """Actor/learner-side handle to a remote ReplayServer."""

    def __init__(self, rpc: Rpc, server_peer: str, name: str):
        self._rpc = rpc
        self._peer = server_peer
        self._name = name

    def add_async(self, items, priorities=None):
        return self._rpc.async_(self._peer, f"{self._name}.add", items, priorities)

    def add(self, items, priorities=None):
        return self._rpc.sync(self._peer, f"{self._name}.add", items, priorities)

    def sample_async(self, batch_size: int):
        return self._rpc.async_(self._peer, f"{self._name}.sample", batch_size)

    def sample(self, batch_size: int):
        out = self._rpc.sync(self._peer, f"{self._name}.sample", batch_size)
        return out["batch"], out["indices"], out["weights"]

    def update_priorities_async(self, indices, priorities):
        return self._rpc.async_(
            self._peer, f"{self._name}.update_priorities", indices, priorities
        )

    def update_priorities(self, indices, priorities) -> None:
        """Fire-and-forget priority write-back (the learner never blocks)."""
        self.update_priorities_async(indices, priorities)

    def size(self) -> int:
        return self._rpc.sync(self._peer, f"{self._name}.size")
