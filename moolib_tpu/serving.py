"""Resilient serving plane: replicated inference with admission control,
zero-downtime weight hot-swap, and request-loss-free client failover
(ROADMAP item 3; docs/RESILIENCE.md failure matrix).

The single-peer ``lm_serve.serve()`` loop reproduces the reference's
cross-caller inference batching (``src/moolib.cc:1007-1178``) but is a
fragile singleton: one process owns the model, clients hard-fail on its
death, and a weight update means a restart.  This module grows it into a
replica fleet behind one dispatch policy (the Podracer layout,
arxiv 2104.06272):

- :class:`ServeService` — the server plane.  A deferred RPC handler admits
  requests through a bounded queue with per-request deadlines
  (:class:`AdmissionController` rejects *immediately*, with a typed
  overload error, anything that cannot meet its deadline given queue depth
  and the EMA batch-service time — instead of letting it time out a minute
  later), dedups retries by request id (a retry racing a slow reply cannot
  double-serve), dynamic-batches to power-of-two buckets, retries a failing
  batch once unbatched (one poisoned request fails only its own caller),
  and installs staged weights *between* service iterations — a hot swap
  never drops or slow-paths a request.
- :class:`ModelPublisher` / :class:`ModelSubscriber` — zero-downtime weight
  distribution as a version-keyed, resumable chunk pull (the PR-3
  accumulator sync idiom at the serving tier): the publisher (the ``lm``
  learner or a standalone pusher) announces ``(version, sha)``; each
  replica pulls chunks into a shadow buffer, verifies the digest, and
  stages the result for the next inter-iteration cutover.  A pull that
  dies with its publisher resumes from the last received chunk.
- :class:`ServeClient` — discovers replicas through the Broker
  (``__broker_list``; replicas register as *non-contributing* cohort
  members via ``Group.set_role``), spreads load by least-outstanding, and
  retries idempotently with capped exponential backoff on replica death.
  A SIGKILLed replica mid-batch costs latency, never a lost request.
- :class:`ServeReplica` — glue: one listening peer = broker registration +
  service + subscriber + group ping pump.

The module is numpy + stdlib only (no jax import): the model step is an
opaque ``step_fn(params, batch) -> outputs`` and weights travel as pickled
host pytrees, so the plane itself stays testable on any box.
"""

from __future__ import annotations

import asyncio
import hashlib
import itertools
import math
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import telemetry, utils
from .group import Group
from .rpc import Future, Rpc, RpcError
from .telemetry import tracing as _tracing

__all__ = [
    "AdmissionController",
    "BrokerUnreachableError",
    "ModelPublisher",
    "ModelSubscriber",
    "ServeClient",
    "ServeDeadlineError",
    "ServeOverloadError",
    "ServeReplica",
    "ServeService",
    "bucket",
    "bucket_shapes",
    "is_overload_error",
]

_REG = telemetry.get_registry()
_M_SWAPS = _REG.counter("serve_hot_swaps_total", "live weight cutovers installed")
_M_SWAP_S = _REG.histogram(
    "serve_swap_seconds",
    "version announce seen -> new weights serving (pull + stage + cutover)",
)
_M_VERSION = _REG.gauge("serve_model_version", "model version currently serving")
_M_REJECTS = _REG.counter(
    "serve_admission_rejects_total",
    "requests rejected at admission (typed overload error)",
    labelnames=("reason",),
)
_M_DEADLINE_MISS = _REG.counter(
    "serve_deadline_misses_total",
    "admitted requests answered after their deadline",
)
_M_DEPTH = _REG.gauge("serve_queue_depth", "admitted requests awaiting service")
_M_BATCH_RETRY = _REG.counter(
    "serve_batch_retries_total",
    "failed batches retried unbatched (blast-radius isolation)",
)
_M_DEDUP = _REG.counter(
    "serve_dedup_hits_total",
    "requests coalesced onto an in-flight or cached request id",
)
_M_REQS = _REG.counter(
    "serve_requests_total", "requests answered", labelnames=("outcome",)
)
_M_PULL_BYTES = _REG.counter(
    "serve_model_pull_bytes_total", "model chunk bytes pulled by subscribers"
)
_M_PULL_RESUMES = _REG.counter(
    "serve_model_pull_resumes_total",
    "model pulls resumed from a partial chunk buffer",
)
_M_CLIENT_RETRIES = _REG.counter(
    "serve_client_retries_total", "client attempts retried after an error"
)
_M_CLIENT_FAILOVERS = _REG.counter(
    "serve_client_failovers_total", "client attempts moved to another replica"
)
_M_BROKER_FAILOVERS = _REG.counter(
    "serve_client_broker_failovers_total",
    "discovery refreshes moved to a different broker in the list",
)
_M_QPS = _REG.gauge(
    "serve_qps", "requests answered per second (sliding ~1s window)"
)
_M_QWAIT = _REG.gauge(
    "serve_queue_wait_s",
    "EMA of request queue wait, enqueue -> service take (the autoscaler's "
    "serve grow signal)",
)
_M_PAD_TOKENS = _REG.counter(
    "serve_pad_tokens_total",
    "tokens of padding waste: bucket pad rows and decode overrun in the "
    "batch-synchronous arm, prompt-bucket padding in the engine arm — "
    "subtract from gross throughput to get REAL tokens/s",
)
_M_PHASE = _REG.histogram(
    "serve_phase_seconds",
    "per-request serve latency by phase: admission (handler entry -> "
    "enqueue), queue (enqueue -> batch take), batch_assembly (concat + "
    "bucket pad), device (step_fn), reply (responses out)",
    labelnames=("phase",),
)

# Typed overload protocol: remote handler errors travel as strings
# (``RpcError(message)`` on the caller), so the type rides a token in the
# message.  ``ret.error(OVERLOAD_TOKEN + ...)`` server-side; clients decode
# with :func:`is_overload_error` and surface :class:`ServeOverloadError`.
OVERLOAD_TOKEN = "__serve_overload__"


class ServeOverloadError(RpcError):
    """Typed admission rejection: the replica (or every replica) determined
    the request cannot meet its deadline — surfaced immediately, not after
    a transport timeout."""


class ServeDeadlineError(RpcError):
    """The client-side deadline expired before any replica answered."""


class BrokerUnreachableError(RpcError):
    """Every broker in the discovery list has been unreachable past the
    client's patience window: the client cannot learn a roster at all.
    Typed so callers can tell a dead control plane (page the operator)
    from a slow or overloaded replica fleet (back off and retry).  Like
    replica errors, failing brokers are suspected with capped exponential
    backoff rather than hammered."""


def is_overload_error(exc: object) -> bool:
    """True for a typed overload: either the client-side
    :class:`ServeOverloadError` or a caller-side error string carrying the
    server's overload token."""
    return isinstance(exc, ServeOverloadError) or OVERLOAD_TOKEN in str(exc)


def bucket(n: int, cap: int) -> int:
    """Next power-of-two >= n, capped: THE batch bucketing policy — the
    startup warmup enumerates exactly these shapes, so a policy change here
    cannot silently desync the two sites (a mid-traffic compile measured as
    7 req/s with multi-second p50 in serve_bench)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def bucket_shapes(cap: int) -> List[int]:
    """Every batch shape :func:`bucket` can produce for ``cap``."""
    shapes, b = [cap], 1
    while b < cap:
        shapes.append(b)
        b *= 2
    return shapes


# --------------------------------------------------------------------------
# admission control
# --------------------------------------------------------------------------
class AdmissionController:
    """Bounded admission in front of the batching queue.

    Two reject conditions, both decided at arrival (the whole point is to
    move the failure from a 60 s client timeout to an immediate typed
    error):

    - ``queue_full``: depth at ``max_queue`` — the classic bounded buffer.
    - ``deadline``: the request carries a deadline budget and the wait
      estimate says it cannot be met.  The estimate is
      ``(batches queued ahead + 1 in service) * EMA batch-service-seconds``
      — deliberately simple and slightly conservative; until a first batch
      has been timed there is no estimate and only ``queue_full`` applies.

    ``per_token=True`` switches the estimate from per-batch to per-token
    units for the continuous-batching engine, where "a batch" is not the
    unit of service: ``note_service(seconds, tokens)`` maintains an EMA of
    seconds-per-emitted-token and the wait estimate is ``pending tokens *
    that EMA``, with the pending-token count supplied by the engine through
    the ``pending_tokens`` callable (called under the service lock — it must
    not block or re-enter).

    Thread-safe; ``note_service`` is fed by the serve loop after every
    batch (or engine decode step).
    """

    def __init__(self, *, max_queue: int = 128, batch_size: int = 16,
                 alpha: float = 0.25, per_token: bool = False,
                 pending_tokens: Optional[Callable[[], int]] = None):
        self.max_queue = int(max_queue)
        self.batch_size = max(1, int(batch_size))
        self.alpha = float(alpha)
        self.per_token = bool(per_token)
        self._pending_tokens = pending_tokens
        self._ema: Optional[float] = None
        self._lock = threading.Lock()

    def note_service(self, seconds: float, tokens: Optional[int] = None) -> None:
        """Feed one service interval.  Per-batch mode ignores ``tokens``;
        per-token mode folds ``seconds / tokens`` into the EMA (a step that
        emitted nothing carries no signal and is dropped)."""
        if self.per_token:
            if not tokens:
                return
            value = float(seconds) / int(tokens)
        else:
            value = float(seconds)
        with self._lock:
            if self._ema is None:
                self._ema = value
            else:
                self._ema += self.alpha * (value - self._ema)

    def ema_batch_seconds(self) -> Optional[float]:
        """The EMA in this controller's service unit: seconds per batch
        (default) or seconds per emitted token (``per_token=True``)."""
        with self._lock:
            return self._ema

    def estimate_wait(self, depth: int) -> Optional[float]:
        """Seconds until a request arriving at ``depth`` would be answered
        (None until a first batch has been timed)."""
        with self._lock:
            ema = self._ema
        if ema is None:
            return None
        if self.per_token:
            if self._pending_tokens is None:
                return None  # engine wiring absent: only queue_full applies
            return self._pending_tokens() * ema
        batches_ahead = math.ceil((depth + 1) / self.batch_size)
        return (batches_ahead + 1) * ema

    def admit(self, depth: int, deadline_s: Optional[float]) -> Optional[str]:
        """None to admit, else the reject reason (``"queue_full"`` /
        ``"deadline"``)."""
        if depth >= self.max_queue:
            return "queue_full"
        if deadline_s is not None:
            est = self.estimate_wait(depth)
            if est is not None and est > float(deadline_s):
                return "deadline"
        return None


# --------------------------------------------------------------------------
# server plane
# --------------------------------------------------------------------------
class _Request:
    __slots__ = ("prompt", "ret", "waiters", "t_enq", "deadline_at", "req_id",
                 "single", "tctx", "max_new")

    def __init__(self, prompt, ret, t_enq, deadline_at, req_id, single,
                 tctx=None, max_new=None):
        self.max_new = max_new  # per-request token budget (None = server default)
        self.prompt = prompt
        self.ret = ret
        self.waiters: List[Any] = []  # dedup'd rets riding the same req_id
        self.t_enq = t_enq
        self.deadline_at = deadline_at
        self.req_id = req_id
        self.single = single
        # Trace context captured at admission (the deferred handler runs
        # under the RPC layer's rpc.recv span) — the service loop's batch
        # span parents under it, crossing the queue/batch thread hop.
        self.tctx = tctx


class ServeService:
    """One replica's service plane: admission -> dedup -> dynamic batching
    -> bucketed ``step_fn`` -> per-caller responses, with staged weights
    installed between iterations.

    ``step_fn(params, batch) -> outputs`` is the whole model contract: a
    2-D numpy batch in, a stacked batch of outputs back (extra pad rows are
    sliced off by the caller's row count).  The serve loop never sees jax.

    Requests arrive through the deferred RPC handler ``name`` with optional
    ``deadline_s`` (remaining budget, seconds) and ``req_id`` kwargs; both
    are optional so plain ``rpc.async_(peer, name, prompt)`` clients keep
    working.  ``{name}_stats`` serves the same counter surface the legacy
    ``serve()`` queue exposed (serve_bench diffs two snapshots) plus the
    resilience counters.
    """

    def __init__(self, rpc: Rpc, step_fn: Callable, params, *,
                 name: str = "generate", version: int = 0,
                 batch_size: int = 16, dynamic_batching: bool = True,
                 max_queue: int = 128, dedup_ttl: float = 60.0,
                 pad_buckets: bool = True,
                 per_request_tokens: bool = False,
                 default_max_new: int = 16):
        self._rpc = rpc
        self._step_fn = step_fn
        self._params = params
        self._name = name
        self._batch_size = int(batch_size)
        self._dynamic = bool(dynamic_batching)
        self._pad_buckets = bool(pad_buckets) and self._dynamic
        self._dedup_ttl = float(dedup_ttl)
        # per_request_tokens: step_fn grows a third argument — an int32
        # per-row token-budget vector — and each caller's reply is sliced
        # to its own budget.  The batch still decodes to the row max (the
        # convoy the engine arm exists to remove); the overrun is counted
        # as pad-token waste so the A/B compares real throughput.
        self._per_request_tokens = bool(per_request_tokens)
        self._default_max_new = int(default_max_new)
        self.admission = AdmissionController(
            max_queue=max_queue,
            batch_size=self._batch_size if self._dynamic else 1,
        )
        # serve_qps window (shared by the engine subclass's loop).
        self._qps_t0 = time.monotonic()
        self._qps_n = 0
        self._lock = threading.Lock()
        self._queue: List[_Request] = []
        self._inflight: Dict[str, _Request] = {}  # req_id -> queued/served req
        self._done: Dict[str, Tuple[Any, Optional[str], float]] = {}
        self._version = int(version)
        self._staged: Optional[Tuple[int, Any, float]] = None
        self._closed = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self._stats = {
            "items": 0, "takes": 0, "wait_s_sum": 0.0, "wait_s_max": 0.0,
            "depth_max": 0, "served": 0, "iterations": 0, "bucket_pad_rows": 0,
            "admission_rejects": 0, "deadline_misses": 0, "dedup_hits": 0,
            "batch_retries": 0, "hot_swaps": 0, "last_swap_seconds": None,
        }
        _M_VERSION.set(self._version)
        rpc.define_deferred(name, self._on_request)
        rpc.define(f"{name}_stats", self.stats)

    # ------------------------------------------------------------- weights
    def stage(self, version: int, params, announced_at: Optional[float] = None):
        """Stage new weights (shadow buffer) for installation between
        service iterations.  ``announced_at`` (monotonic) is when the
        version announcement was first seen — ``serve_swap_seconds``
        measures announce -> serving.  Stale versions are ignored."""
        version = int(version)
        with self._lock:
            if version <= self._version:
                return False
            self._staged = (version, params,
                            announced_at if announced_at is not None
                            else time.monotonic())
        self._wake_loop()
        return True

    def model_version(self) -> int:
        with self._lock:
            return self._version

    def _maybe_swap_locked(self) -> None:
        if self._staged is None:
            return
        version, params, announced_at = self._staged
        self._staged = None
        if version <= self._version:
            return
        self._params = params
        self._version = version
        dt = time.monotonic() - announced_at
        self._stats["hot_swaps"] += 1
        self._stats["last_swap_seconds"] = dt
        _M_SWAPS.inc()
        _M_SWAP_S.observe(dt)
        _M_VERSION.set(version)
        telemetry.flight_event("serve.hot_swap", endpoint=self._name,
                               version=version, seconds=round(dt, 4))
        utils.log_info(
            "serve %s: hot-swapped to model version %d in %.3fs",
            self._name, version, dt,
        )

    # ------------------------------------------------------------ admission
    def _on_request(self, ret, prompt, max_new_tokens=None,
                    deadline_s: Optional[float] = None,
                    req_id: Optional[str] = None):
        # max_new_tokens rides positionally after the prompt so
        # ``client.submit(prompt, max_new)`` works against both serving
        # arms; legacy single-argument callers get the server default.
        now = time.monotonic()
        with self._lock:
            if self._closed:
                ret.error(f"serve {self._name}: closed")
                return
            if req_id is not None:
                done = self._done.get(req_id)
                if done is not None:
                    value, err, _t = done
                    self._stats["dedup_hits"] += 1
                    _M_DEDUP.inc()
                    if err is None:
                        ret(value)
                    else:
                        ret.error(err)
                    return
                cur = self._inflight.get(req_id)
                if cur is not None:
                    # A retry raced the original (slow reply, duplicated
                    # frame): attach, never re-serve.
                    cur.waiters.append(ret)
                    self._stats["dedup_hits"] += 1
                    _M_DEDUP.inc()
                    return
            reason = self.admission.admit(len(self._queue), deadline_s)
            if reason is not None:
                self._stats["admission_rejects"] += 1
                _M_REJECTS.inc(reason=reason)
                est = self.admission.estimate_wait(len(self._queue))
                ret.error(
                    f"{OVERLOAD_TOKEN}:{reason}: depth={len(self._queue)} "
                    f"est_wait={est if est is None else round(est, 4)}s "
                    f"deadline={deadline_s}s"
                )
                return
            arr = np.asarray(prompt)
            req = _Request(
                prompt=arr[None] if arr.ndim == 1 else arr,
                ret=ret,
                t_enq=now,
                deadline_at=None if deadline_s is None else now + float(deadline_s),
                req_id=req_id,
                single=arr.ndim == 1,
                tctx=telemetry.current_context(),
                max_new=None if max_new_tokens is None else int(max_new_tokens),
            )
            self._queue.append(req)
            if req_id is not None:
                self._inflight[req_id] = req
            self._stats["depth_max"] = max(self._stats["depth_max"],
                                           len(self._queue))
            _M_DEPTH.inc()
        _M_PHASE.observe(time.monotonic() - now, phase="admission")
        self._wake_loop()

    def _wake_loop(self) -> None:
        loop, wake = self._loop, self._wake
        if loop is not None and wake is not None:
            try:
                loop.call_soon_threadsafe(wake.set)
            except RuntimeError:
                pass  # loop already closed

    # -------------------------------------------------------------- service
    def _take_locked(self) -> List[_Request]:
        if not self._queue:
            return []
        n = len(self._queue) if self._dynamic else 1
        n = min(n, self._batch_size)
        batch, self._queue = self._queue[:n], self._queue[n:]
        now = time.monotonic()
        s = self._stats
        s["takes"] += 1
        s["items"] += n
        _M_DEPTH.dec(n)
        for r in batch:
            wait = now - r.t_enq
            s["wait_s_sum"] += wait
            s["wait_s_max"] = max(s["wait_s_max"], wait)
            _M_PHASE.observe(wait, phase="queue")
            self._note_queue_wait(wait)
        return batch

    # Smoothed queue wait + answered-per-second gauges: the autoscaler's
    # serve signals (PeerSample.serve_wait / serve_qps).
    _WAIT_ALPHA = 0.3

    def _note_queue_wait(self, wait: float) -> None:
        ema = getattr(self, "_wait_ema", None)
        self._wait_ema = (wait if ema is None
                          else ema + self._WAIT_ALPHA * (wait - ema))
        _M_QWAIT.set(self._wait_ema)

    def _note_answered(self, n: int, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._qps_n += n
        dt = now - self._qps_t0
        if dt >= 1.0:
            _M_QPS.set(self._qps_n / dt)
            self._qps_t0, self._qps_n = now, 0

    def _respond(self, req: _Request, value, err: Optional[str]) -> None:
        now = time.monotonic()
        if err is None and req.deadline_at is not None and now > req.deadline_at:
            self._stats["deadline_misses"] += 1
            _M_DEADLINE_MISS.inc()
        _M_REQS.inc(outcome="ok" if err is None else "error")
        rets = [req.ret] + req.waiters
        with self._lock:
            if req.req_id is not None:
                self._inflight.pop(req.req_id, None)
                self._done[req.req_id] = (value, err, now)
        for ret in rets:
            try:
                if err is None:
                    ret(value)
                else:
                    ret.error(err)
            except Exception:  # noqa: BLE001 — a dead caller must not stop
                pass           # the batch's remaining responses

    def _sweep_done_locked(self, now: float) -> None:
        if not self._done:
            return
        dead = [k for k, (_v, _e, t) in self._done.items()
                if now - t > self._dedup_ttl]
        for k in dead:
            del self._done[k]

    def _run_batch(self, batch: List[_Request]) -> None:
        # The batch serves under the first traced request's context — one
        # representative cross-host edge per step_fn call (per-request edges
        # would draw N identical arrows onto the same device work).
        parent = next((r.tctx for r in batch if r.tctx is not None), None)
        with telemetry.child_span(f"serve.batch {self._name}", parent,
                                  requests=len(batch)):
            t_asm = time.monotonic()
            prompts = np.concatenate([r.prompt for r in batch], axis=0)
            n = prompts.shape[0]
            budgets = None
            if self._per_request_tokens:
                budgets = np.concatenate([
                    np.full(r.prompt.shape[0],
                            r.max_new if r.max_new else self._default_max_new,
                            dtype=np.int32)
                    for r in batch
                ])
            if self._pad_buckets and n < self._batch_size:
                b = bucket(n, self._batch_size)
                if n < b:
                    pad = np.repeat(prompts[-1:], b - n, axis=0)
                    prompts = np.concatenate([prompts, pad], axis=0)
                    self._stats["bucket_pad_rows"] += b - n
                    # Pad rows burn a full prompt + decode budget each.
                    waste = (b - n) * prompts.shape[1]
                    if budgets is not None:
                        budgets = np.concatenate([
                            budgets,
                            np.full(b - n, budgets.max(), dtype=np.int32),
                        ])
                        waste += (b - n) * int(budgets.max())
                    _M_PAD_TOKENS.inc(waste)
            if budgets is not None:
                # The convoy cost of batch-synchronous decode, made visible:
                # every row steps to the batch max budget.
                _M_PAD_TOKENS.inc(int((budgets[:n].max() - budgets[:n]).sum()))
            t0 = time.monotonic()
            _M_PHASE.observe(t0 - t_asm, phase="batch_assembly")
            step_args = (prompts,) if budgets is None else (prompts, budgets)
            try:
                out = np.asarray(self._step_fn(self._params, *step_args))[:n]
            except Exception as e:  # noqa: BLE001
                if len(batch) == 1:
                    # Already unbatched: the failure belongs to this caller.
                    self._respond(batch[0], None, f"generate failed: {e}")
                    return
                # Blast-radius isolation: one poisoned request must not error
                # every caller stacked into its batch — retry once, unbatched,
                # so only the offender fails.
                self._stats["batch_retries"] += 1
                _M_BATCH_RETRY.inc()
                for req in batch:
                    rows = req.prompt.shape[0]
                    try:
                        args = ((req.prompt,) if budgets is None else
                                (req.prompt, np.full(
                                    rows,
                                    req.max_new if req.max_new
                                    else self._default_max_new,
                                    dtype=np.int32)))
                        o = np.asarray(self._step_fn(self._params, *args))[:rows]
                    except Exception as e2:  # noqa: BLE001
                        self._respond(req, None, f"generate failed: {e2}")
                        continue
                    self._respond(req, self._clip(req, o), None)
                return
            dt = time.monotonic() - t0
            if budgets is not None:
                self.admission.note_service(
                    dt, tokens=int(budgets[:n].sum())
                )
            else:
                self.admission.note_service(dt)
            _M_PHASE.observe(dt, phase="device")
            t_reply = time.monotonic()
            i = 0
            for req in batch:
                rows = req.prompt.shape[0]
                part = out[i:i + rows]
                i += rows
                self._respond(req, self._clip(req, part), None)
            _M_PHASE.observe(time.monotonic() - t_reply, phase="reply")

    def _clip(self, req: _Request, rows: np.ndarray):
        """Slice one request's output rows down to its own token budget
        (per-request-tokens mode decodes the whole batch to the row max)."""
        if self._per_request_tokens and rows.ndim == 2:
            budget = req.max_new if req.max_new else self._default_max_new
            tp = req.prompt.shape[1]
            rows = rows[:, :tp + budget]
        return rows[0] if req.single else rows

    async def loop(self, total=None) -> int:
        """Serve until ``total`` requests have been answered (None =
        forever, until :meth:`close`).  Returns the number of service
        iterations — with concurrent callers this is smaller than the
        request count, which is the point of dynamic batching."""
        self._loop = asyncio.get_event_loop()
        self._wake = asyncio.Event()
        served = 0
        try:
            while not self._closed and (total is None or served < total):
                with self._lock:
                    self._maybe_swap_locked()
                    batch = self._take_locked()
                    self._sweep_done_locked(time.monotonic())
                if not batch:
                    # Park until a request or a staged swap wakes us; the
                    # timeout bounds a lost wakeup AND gives idle replicas a
                    # swap-install cadence (a swap must not wait for
                    # traffic).
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
                    self._wake.clear()
                    # Close the rate window even with nothing answered, so
                    # serve_qps decays to the true (zero) rate under silence
                    # — the autoscaler's idle-shrink signal reads it.  Same
                    # for the wait EMA: an empty queue means waits are now
                    # zero, not whatever the last busy spell left behind.
                    self._note_answered(0)
                    if not self._queue:
                        self._note_queue_wait(0.0)
                    continue
                rows = sum(r.prompt.shape[0] for r in batch)
                served += rows
                self._stats["iterations"] += 1
                self._stats["served"] += rows
                self._run_batch(batch)
                self._note_answered(len(batch))
        finally:
            self._loop = None
            self._wake = None
        return self._stats["iterations"]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._stats)
            out["batch_size"] = self._batch_size if self._dynamic else 1
            out["depth"] = len(self._queue)
            out["model_version"] = self._version
            out["ema_batch_seconds"] = self.admission.ema_batch_seconds()
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            queue, self._queue = self._queue, []
            self._inflight.clear()
        _M_DEPTH.dec(len(queue))
        for req in queue:
            for ret in [req.ret] + req.waiters:
                try:
                    ret.error(f"serve {self._name}: closed")
                except Exception:  # noqa: BLE001
                    pass
        self._wake_loop()
        self._rpc.undefine(self._name)
        self._rpc.undefine(f"{self._name}_stats")


# --------------------------------------------------------------------------
# model distribution (publisher / subscriber)
# --------------------------------------------------------------------------
def _model_chunk_bytes() -> int:
    import os

    return max(1, int(os.environ.get("MOOLIB_MODEL_CHUNK_BYTES", str(1 << 20))))


class ModelPublisher:
    """Version announcement + resumable chunk source for serving weights.

    Holds the latest published payload as ``(version, sha, chunks)`` and
    serves two endpoints (``{name}_meta`` / ``{name}_chunk``): subscribers
    poll the meta, pull chunks by sequence number, and verify the digest —
    the PR-3 accumulator model-sync idiom, inverted into a *pull* so the
    publisher needs no replica roster and a pull that dies with either end
    resumes from the subscriber's partial buffer (same ``(version, sha)``
    key).  Publishing a newer version mid-pull invalidates older chunk
    requests (the handler answers None), which is how stale pulls abort.

    The payload is an arbitrary picklable pytree; callers publishing jax
    params should ``jax.device_get`` them first.
    """

    def __init__(self, rpc: Rpc, *, name: str = "model",
                 chunk_bytes: Optional[int] = None):
        self._rpc = rpc
        self._name = name
        self._chunk_bytes = int(chunk_bytes) if chunk_bytes else _model_chunk_bytes()
        self._lock = threading.Lock()
        self._meta: Optional[Dict[str, Any]] = None
        self._chunks: List[bytes] = []
        rpc.define(f"{name}_meta", self._on_meta)
        rpc.define(f"{name}_chunk", self._on_chunk)

    def publish(self, payload, version: int) -> Dict[str, Any]:
        """Make ``payload`` the announced model at ``version``.  Returns the
        meta dict subscribers will see."""
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        sha = hashlib.sha256(blob).hexdigest()[:16]
        cb = self._chunk_bytes
        chunks = [blob[i:i + cb] for i in range(0, len(blob), cb)] or [b""]
        meta = {
            "version": int(version), "sha": sha, "nbytes": len(blob),
            "total": len(chunks), "chunk_bytes": cb,
        }
        with self._lock:
            self._meta, self._chunks = meta, chunks
        utils.log_info(
            "publisher %s: announced model version %d (%d bytes, %d chunks)",
            self._name, version, len(blob), len(chunks),
        )
        return dict(meta)

    def _on_meta(self):
        with self._lock:
            return dict(self._meta) if self._meta is not None else None

    def _on_chunk(self, version: int, sha: str, seq: int):
        with self._lock:
            if (self._meta is None or self._meta["version"] != version
                    or self._meta["sha"] != sha):
                return None  # stale pull: subscriber must re-poll the meta
            if not 0 <= seq < len(self._chunks):
                return None
            return self._chunks[seq]

    def close(self) -> None:
        self._rpc.undefine(f"{self._name}_meta")
        self._rpc.undefine(f"{self._name}_chunk")


class ModelSubscriber:
    """Replica-side puller: polls a :class:`ModelPublisher`'s meta, pulls
    new versions chunk-by-chunk (windowed) into a shadow buffer, verifies
    the sha, and hands the decoded payload to ``on_update(version, payload,
    announced_at)``.

    The chunk buffer is keyed by ``(version, sha)`` and survives failed
    pulls: a publisher restart mid-transfer (same payload, same key)
    resumes from the last received chunk instead of starting over
    (``serve_model_pull_resumes_total``).  A *newer* announced version
    abandons the partial pull — serving wants the freshest weights, not a
    completed stale transfer.
    """

    def __init__(self, rpc: Rpc, publisher: str, *, name: str = "model",
                 on_update: Callable[[int, Any, float], None],
                 poll_interval: float = 0.5, window: int = 4,
                 timeout: float = 10.0):
        self._rpc = rpc
        self._publisher = publisher
        self._name = name
        self._on_update = on_update
        self._poll_interval = float(poll_interval)
        self._window = max(1, int(window))
        self._timeout = float(timeout)
        self._have_version: Optional[int] = None
        self._buffer_key: Optional[Tuple[int, str]] = None
        self._buffer: List[Optional[bytes]] = []
        self._announced: Dict[Tuple[int, str], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ModelSubscriber":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"model-sub-{self._name}", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------------ run
    def _poll_meta(self) -> Optional[Dict[str, Any]]:
        try:
            return self._rpc.async_(
                self._publisher, f"{self._name}_meta"
            ).result(self._timeout)
        except Exception:  # noqa: BLE001 — publisher absent/restarting is
            return None    # a normal serving state, not an error

    def _run(self) -> None:
        while not self._stop.is_set():
            meta = self._poll_meta()
            if meta is not None and (self._have_version is None
                                     or meta["version"] > self._have_version):
                key = (meta["version"], meta["sha"])
                # announce time: the FIRST sighting of this (version, sha);
                # serve_swap_seconds is measured from here.
                self._announced.setdefault(key, time.monotonic())
                self._pull(meta)
            self._stop.wait(self._poll_interval)

    def _pull(self, meta: Dict[str, Any]) -> None:
        key = (meta["version"], meta["sha"])
        total = int(meta["total"])
        if self._buffer_key != key:
            self._buffer_key = key
            self._buffer = [None] * total
        elif any(c is not None for c in self._buffer):
            _M_PULL_RESUMES.inc()
            utils.log_info(
                "subscriber %s: resuming pull of version %d from chunk %d/%d",
                self._name, meta["version"],
                sum(c is not None for c in self._buffer), total,
            )
        missing = [i for i, c in enumerate(self._buffer) if c is None]
        for start in range(0, len(missing), self._window):
            if self._stop.is_set():
                return
            seqs = missing[start:start + self._window]
            futs = [
                self._rpc.async_(self._publisher, f"{self._name}_chunk",
                                 meta["version"], meta["sha"], seq)
                for seq in seqs
            ]
            for seq, fut in zip(seqs, futs):
                try:
                    data = fut.result(self._timeout)
                except Exception:  # noqa: BLE001 — publisher died mid-pull;
                    return         # buffer kept, next poll resumes
                if data is None:
                    # Stale (a newer version superseded this one mid-pull):
                    # abandon, the next meta poll redirects us.
                    return
                self._buffer[seq] = bytes(data)
                _M_PULL_BYTES.inc(len(data))
        blob = b"".join(self._buffer)  # type: ignore[arg-type]
        if hashlib.sha256(blob).hexdigest()[:16] != meta["sha"]:
            utils.log_error(
                "subscriber %s: sha mismatch for version %d; discarding",
                self._name, meta["version"],
            )
            self._buffer_key, self._buffer = None, []
            return
        payload = pickle.loads(blob)
        self._have_version = int(meta["version"])
        self._buffer_key, self._buffer = None, []
        announced = self._announced.pop(key, time.monotonic())
        self._announced = {k: t for k, t in self._announced.items()
                           if k[0] > meta["version"]}
        self._on_update(self._have_version, payload, announced)


# --------------------------------------------------------------------------
# client plane
# --------------------------------------------------------------------------
class ServeClient:
    """Request-loss-free client: replica discovery, load spreading, and
    idempotent retry with capped exponential backoff.

    Two discovery modes:

    - ``broker="host:port"``: connect to the Broker, refresh the live
      replica roster from ``__broker_list`` (replicas register as
      non-contributing ``Group`` observers), and reach replicas by name
      through gossip peer-finding.
    - ``replicas=["name", ...]``: a static roster; the caller is
      responsible for connecting ``rpc`` somewhere that can route to them.

    Every logical request gets one ``req_id`` reused across attempts, so
    server-side dedup makes retries idempotent: a retry racing a slow reply
    attaches to the in-flight computation instead of re-serving.  Failure
    handling per attempt:

    - typed overload reject -> immediately fail over to a not-yet-rejecting
      replica; when every known replica has rejected, surface
      :class:`ServeOverloadError` (don't burn the deadline on a fleet that
      already said no);
    - any other error (replica death, transport timeout) -> capped
      exponential backoff, then retry on the healthiest replica.

    ``metadata=False`` drops the ``deadline_s``/``req_id`` kwargs for
    legacy ``serve()`` endpoints whose dynamic-batching queue stacks
    kwargs across callers (the ``--connect`` single-shot baseline).
    """

    def __init__(self, rpc: Optional[Rpc] = None, *, fn: str = "generate",
                 replicas: Sequence[str] = (), broker: Optional[str] = None,
                 brokers: Sequence[str] = (),
                 broker_name: str = "broker", group: str = "serve",
                 deadline_s: float = 30.0, attempt_timeout: float = 5.0,
                 max_attempts: int = 6, backoff: float = 0.05,
                 backoff_cap: float = 1.0, refresh_interval: float = 0.5,
                 broker_unreachable_after: float = 10.0,
                 metadata: bool = True):
        self._owns_rpc = rpc is None
        if rpc is None:
            rpc = Rpc()
            rpc.set_name(f"serve-client-{utils.create_uid()[:8]}")
        self._rpc = rpc
        self.fn = fn
        self.deadline_s = float(deadline_s)
        self.attempt_timeout = float(attempt_timeout)
        self.max_attempts = int(max_attempts)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.metadata = bool(metadata)
        self._broker_name = broker_name
        self._group = group
        self._lock = threading.Lock()
        self._replicas: List[str] = list(replicas)
        self._outstanding: Dict[str, int] = {}
        self._suspect: Dict[str, float] = {}  # replica -> suspect-until
        self._rr = itertools.count()
        self._ids = itertools.count()
        self._closed = threading.Event()
        self._stats = {"ok": 0, "overload": 0, "deadline": 0, "error": 0,
                       "retries": 0, "failovers": 0}
        self._refresh_thread: Optional[threading.Thread] = None
        # Discovery control plane: one broker (legacy) or the full HA list.
        # Re-resolved from ADDRESSES on every refresh — a cached name would
        # pin discovery to whichever broker was primary at construction.
        self._broker_addrs: List[str] = (
            ([broker] if broker else []) + [b for b in brokers if b]
        )
        self._broker_addr: Optional[str] = None  # address currently serving us
        self._broker_suspect: Dict[str, float] = {}  # addr -> suspect-until
        self._broker_backoff: Dict[str, float] = {}  # addr -> current backoff
        self._broker_unreachable_after = float(broker_unreachable_after)
        self._broker_ok_at = time.monotonic()
        if self._broker_addrs:
            for a in self._broker_addrs:
                rpc.connect(a)
            self._refresh_thread = threading.Thread(
                target=self._refresh_loop, args=(float(refresh_interval),),
                name="serve-client-refresh", daemon=True,
            )
            self._refresh_thread.start()

    # -------------------------------------------------------------- roster
    def _refresh_loop(self, interval: float) -> None:
        while not self._closed.is_set():
            self._refresh_once()
            self._closed.wait(interval)

    def _refresh_once(self) -> None:
        """One discovery pass across the broker list: current broker first,
        suspects skipped while their backoff runs (unless everyone is
        suspect), a primary's roster preferred over a standby's replicated
        one (the standby keeps discovery alive mid-failover)."""
        now = time.monotonic()
        addrs = list(self._broker_addrs)
        if self._broker_addr in addrs:
            addrs.remove(self._broker_addr)
            addrs.insert(0, self._broker_addr)
        candidates = [a for a in addrs
                      if self._broker_suspect.get(a, 0.0) <= now] or addrs
        best: Optional[Tuple[str, dict]] = None
        for addr in candidates:
            name = self._rpc.peer_name_at(addr)
            if name is None:  # never greeted: down, or still dialing
                self._note_broker_fail(addr, now)
                continue
            try:
                listing = self._rpc.async_(
                    name, "__broker_list", self._group
                ).result(2.0)
            except Exception:  # noqa: BLE001
                self._note_broker_fail(addr, now)
                continue
            if not isinstance(listing, dict):
                self._note_broker_fail(addr, now)
                continue
            self._broker_suspect.pop(addr, None)
            self._broker_backoff.pop(addr, None)
            if not listing.get("standby"):
                best = (addr, listing)
                break
            if best is None:
                best = (addr, listing)
        if best is None:
            return  # everyone unreachable: keep the last-known roster
        addr, listing = best
        if self._broker_addr is not None and addr != self._broker_addr:
            _M_BROKER_FAILOVERS.inc()
            utils.log_info("serve client: discovery failed over to broker at %s",
                           addr)
        self._broker_addr = addr
        self._broker_ok_at = time.monotonic()
        if listing.get("observers"):
            with self._lock:
                self._replicas = sorted(listing["observers"])

    def _note_broker_fail(self, addr: str, now: float) -> None:
        backoff = self._broker_backoff.get(addr, 0.25)
        self._broker_backoff[addr] = min(backoff * 2, 2.0)
        self._broker_suspect[addr] = now + backoff

    def broker_unreachable(self) -> bool:
        """True when broker discovery is enabled and NO broker in the list
        has answered for ``broker_unreachable_after`` seconds."""
        if not self._broker_addrs or self._refresh_thread is None:
            return False
        return (time.monotonic() - self._broker_ok_at
                > self._broker_unreachable_after)

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def wait_for_replicas(self, n: int = 1, timeout: float = 30.0) -> List[str]:
        """Block until discovery has found ``n`` live replicas."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reps = self.replicas()
            if len(reps) >= n:
                return reps
            if not reps and self.broker_unreachable():
                raise BrokerUnreachableError(
                    f"no broker reachable (tried {self._broker_addrs}) and "
                    f"no replicas known"
                )
            time.sleep(0.05)
        raise ServeDeadlineError(
            f"discovered {len(self.replicas())}/{n} replicas within {timeout}s"
        )

    def _pick(self, tried: set, overloaded: set) -> Optional[str]:
        now = time.monotonic()
        replicas = self.replicas()
        candidates = [r for r in replicas if r not in overloaded]
        if not candidates:
            return None
        healthy = [r for r in candidates
                   if self._suspect.get(r, 0.0) <= now] or candidates
        fresh = [r for r in healthy if r not in tried] or healthy
        with self._lock:
            return min(fresh, key=lambda r: (self._outstanding.get(r, 0), r))

    # ------------------------------------------------------------- request
    def submit(self, *args, deadline_s: Optional[float] = None) -> Future:
        """Fire one logical request; the returned Future resolves with the
        reply, or raises :class:`ServeOverloadError` /
        :class:`ServeDeadlineError` / :class:`RpcError`."""
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        st = {
            "id": f"{self._rpc.get_name()}:{next(self._ids)}",
            "args": args,
            "deadline": time.monotonic() + budget,
            "attempt": 0,
            "tried": set(),
            "overloaded": set(),
            "future": Future(),
            "replica": None,
            # Root of the request's distributed trace.  The span itself is
            # recorded at completion (retries outlive this stack frame);
            # each attempt attaches the context so its rpc.call — and the
            # replica's handler spans across the wire — parent under it.
            "tctx": _tracing.TraceContext(
                _tracing.new_trace_id(), _tracing.new_span_id()
            ),
            # mtlint: allow-bare-timer(span timestamp: the tracer consumes raw perf_counter_ns t0/duration pairs, not a histogram)
            "t0_ns": time.perf_counter_ns(),
        }
        self._attempt(st)
        return st["future"]

    def call(self, *args, deadline_s: Optional[float] = None):
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        return self.submit(*args, deadline_s=deadline_s).result(budget + 5.0)

    def _fail(self, st: Dict[str, Any], exc: RpcError, outcome: str) -> None:
        self._stats[outcome] = self._stats.get(outcome, 0) + 1
        self._record_request_span(st, outcome)
        st["future"].set_exception(exc)

    def _record_request_span(self, st: Dict[str, Any], outcome: str) -> None:
        ctx = st.get("tctx")
        if ctx is None:
            return
        _tracing.get_tracer().record(
            "serve.request",
            st["t0_ns"],
            time.perf_counter_ns() - st["t0_ns"],  # mtlint: allow-bare-timer(span duration for tracer.record, exported via the trace plane)
            trace_id=ctx.trace_id,
            span_id=ctx.span_id,
            args={"req_id": st["id"], "outcome": outcome,
                  "attempts": st["attempt"] + 1},
        )

    def _later(self, st: Dict[str, Any], delay: float) -> None:
        if self._closed.is_set():
            self._fail(st, RpcError("ServeClient closed"), "error")
            return
        t = threading.Timer(delay, self._attempt, args=(st,))
        t.daemon = True
        t.start()

    def _attempt(self, st: Dict[str, Any]) -> None:
        if self._closed.is_set():
            self._fail(st, RpcError("ServeClient closed"), "error")
            return
        now = time.monotonic()
        remaining = st["deadline"] - now
        if remaining <= 0:
            self._fail(st, ServeDeadlineError(
                f"deadline expired after {st['attempt']} attempt(s)"
            ), "deadline")
            return
        replica = self._pick(st["tried"], st["overloaded"])
        if replica is None:
            if st["overloaded"]:
                self._fail(st, ServeOverloadError(
                    f"all replicas rejected: {sorted(st['overloaded'])}"
                ), "overload")
                return
            if not self.replicas() and self.broker_unreachable():
                # Dead control plane, empty roster: a typed error NOW beats
                # burning the deadline re-polling a discovery endpoint that
                # every broker in the list has stopped answering.
                self._fail(st, BrokerUnreachableError(
                    f"no broker reachable (tried {self._broker_addrs}) and "
                    f"no replicas known"
                ), "error")
                return
            # No replicas known yet (discovery warming up, or the whole
            # fleet died): keep polling the roster until the deadline.
            self._later(st, 0.1)
            return
        if st["replica"] is not None and replica != st["replica"]:
            self._stats["failovers"] += 1
            _M_CLIENT_FAILOVERS.inc()
        st["replica"] = replica
        st["tried"].add(replica)
        with self._lock:
            self._outstanding[replica] = self._outstanding.get(replica, 0) + 1
        kwargs = ({"deadline_s": remaining, "req_id": st["id"]}
                  if self.metadata else {})
        with _tracing.attach_context(st["tctx"]):
            fut = self._rpc.async_(replica, self.fn, *st["args"], **kwargs)
        # Per-attempt watchdog: the engine's own timeout is per-Rpc and far
        # too slow for failover; cancelling routes through the same done
        # callback as a transport error.
        watchdog = threading.Timer(min(self.attempt_timeout, remaining),
                                   fut.cancel)
        watchdog.daemon = True
        watchdog.start()
        fut.add_done_callback(
            lambda f, st=st, wd=watchdog, r=replica: self._on_reply(st, wd, r, f)
        )

    def _on_reply(self, st: Dict[str, Any], watchdog, replica: str, fut) -> None:
        watchdog.cancel()
        with self._lock:
            left = self._outstanding.get(replica, 1) - 1
            if left > 0:
                self._outstanding[replica] = left
            else:
                self._outstanding.pop(replica, None)
        exc = fut.exception()
        if exc is None:
            self._stats["ok"] += 1
            self._record_request_span(st, "ok")
            st["future"].set_result(fut._result)
            return
        if is_overload_error(exc):
            st["overloaded"].add(replica)
            self._attempt(st)  # immediate: another replica may have room
            return
        # Replica death / transport timeout / cancellation: suspect it,
        # back off, retry (same req_id -> idempotent server-side).
        self._suspect[replica] = time.monotonic() + 2.0
        st["attempt"] += 1
        if st["attempt"] >= self.max_attempts:
            self._fail(st, RpcError(
                f"request {st['id']} failed after {st['attempt']} attempts: {exc}"
            ), "error")
            return
        self._stats["retries"] += 1
        _M_CLIENT_RETRIES.inc()
        delay = min(self.backoff * (2 ** (st["attempt"] - 1)), self.backoff_cap)
        self._later(st, delay)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def close(self) -> None:
        self._closed.set()
        if self._refresh_thread is not None:
            self._refresh_thread.join(timeout=2.0)
            self._refresh_thread = None
        if self._owns_rpc:
            self._rpc.close()


# --------------------------------------------------------------------------
# replica glue
# --------------------------------------------------------------------------
class ServeReplica:
    """One serving peer: broker registration (non-contributing observer),
    the :class:`ServeService` plane, and an optional :class:`ModelSubscriber`
    feeding hot swaps.

    ``rpc`` must already be named and listening.  With ``broker`` set, the
    replica connects there, joins ``group`` with role ``"replica"`` (so
    ``ServeClient`` discovery sees it without ever touching the training
    cohort's membership epoch), and pumps the group ping from a background
    thread.  With ``publisher`` set, a subscriber polls it for new model
    versions and stages them on the service.
    """

    def __init__(self, rpc: Rpc, step_fn: Optional[Callable], params, *,
                 name: str = "generate", version: int = 0,
                 batch_size: int = 16, dynamic_batching: bool = True,
                 max_queue: int = 128, broker: Optional[str] = None,
                 brokers: Sequence[str] = (),
                 broker_name: str = "broker", group: str = "serve",
                 role: str = "replica", publisher: Optional[str] = None,
                 model_channel: str = "model", poll_interval: float = 0.5,
                 per_request_tokens: bool = False, default_max_new: int = 16,
                 service: Optional[ServeService] = None):
        self._rpc = rpc
        # Every replica is scrapable/profilable by the cohort aggregator.
        telemetry.install_rpc_handlers(rpc)
        # A pre-built service (e.g. engine.EngineService — continuous
        # batching under the same admission/dedup/hot-swap contract) plugs
        # in here; otherwise the classic batch-synchronous plane is built.
        self.service = service if service is not None else ServeService(
            rpc, step_fn, params, name=name, version=version,
            batch_size=batch_size, dynamic_batching=dynamic_batching,
            max_queue=max_queue, per_request_tokens=per_request_tokens,
            default_max_new=default_max_new,
        )
        self._group: Optional[Group] = None
        self._pump: Optional[threading.Thread] = None
        self._stop = threading.Event()
        broker_addrs = ([broker] if broker else []) + [b for b in brokers if b]
        if broker_addrs:
            self._group = Group(rpc, group)
            self._group.set_broker_name(broker_name)
            self._group.set_role(role)
            if brokers:
                # HA mode: the group dials every broker, resolves names from
                # the greetings, and fails its registration pings over when
                # the primary dies (the replica stays discoverable).
                self._group.set_brokers(broker_addrs)
            else:
                rpc.connect(broker_addrs[0])
            self._pump = threading.Thread(
                target=self._pump_loop, name="serve-replica-pump", daemon=True
            )
            self._pump.start()
        self.subscriber: Optional[ModelSubscriber] = None
        if publisher is not None:
            self.subscriber = ModelSubscriber(
                rpc, publisher, name=model_channel,
                on_update=self._on_model, poll_interval=poll_interval,
            ).start()

    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._group.update()
            except Exception:  # noqa: BLE001
                utils.log_verbose("serve replica: group update failed")
            self._stop.wait(0.25)

    def _on_model(self, version: int, payload, announced_at: float) -> None:
        self.service.stage(version, payload, announced_at)

    def loop(self, total=None):
        """The service coroutine; run it under ``asyncio.run``."""
        return self.service.loop(total=total)

    def close(self) -> None:
        self._stop.set()
        if self.subscriber is not None:
            self.subscriber.stop()
        if self._pump is not None:
            self._pump.join(timeout=2.0)
        if self._group is not None:
            try:
                self._group.leave(timeout=1.0)
            except Exception:  # noqa: BLE001
                pass
        self.service.close()
