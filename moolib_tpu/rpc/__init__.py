"""RPC subsystem: serialization, transports, the Rpc engine."""

from . import serialization  # noqa: F401
from .core import (  # noqa: F401
    FrameTooLargeError,
    Future,
    Queue,
    Rpc,
    RpcDeferredReturn,
    RpcError,
    parse_address,
)
