"""RPC subsystem: serialization, transports, the Rpc engine."""

from . import serialization  # noqa: F401
from .core import Future, Queue, Rpc, RpcDeferredReturn, RpcError, parse_address  # noqa: F401
