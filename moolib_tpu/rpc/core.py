"""The RPC engine: peers, transports, futures, function registry.

TPU-native re-design of the reference's RPC core (``src/rpc.{h,cc}``,
``src/transports/``, ``src/moolib.cc`` bindings).  Same capabilities and
Python API:

- ``Rpc``: set_name/listen/connect/define/define_deferred/define_queue/
  undefine/async_/async_callback/sync/set_timeout/set_transports/debug_info
- ``Future`` with ``result/wait/done/cancel/exception`` and asyncio
  ``__await__`` integration
- transports: TCP (``tcp://`` or bare ``host:port``) and Unix-domain sockets
  (``ipc://path``); peers may hold several transports at once and the engine
  picks the lowest-latency one per message (EMA-scored, the analogue of the
  reference's bandit ``src/rpc.cc:640-716``)
- peer discovery by name: greeting exchange on connect plus gossip lookup
  through already-connected peers (reference ``findPeersImpl``
  ``src/rpc.cc:2332-2433``)
- reliability: explicit connections auto-reconnect with backoff, outstanding
  requests are resent on reconnect, receivers deduplicate by (peer-uid, rid)
  for at-most-once execution (reference poke/ack/nack/resend + ``recentIncoming``
  machinery, ``src/rpc.cc:2526-2703``), calls error out after a configurable
  timeout (default 120 s) with ``Call (peer::fn) timed out``.

Architecturally this is *not* a translation: instead of a hand-rolled epoll
poll-thread + lock-free scheduler, each ``Rpc`` runs one asyncio event loop on
a dedicated thread (the IO plane) and dispatches user handlers onto a shared
thread pool (the compute plane).  jax arrays ride the serialization layer's
out-of-band buffer path (host staging), so handlers can freely pass
``jax.Array`` pytrees.
"""

from __future__ import annotations

import asyncio
import atexit
import collections
import concurrent.futures
import contextlib
import itertools
import math
import os
import random
import struct
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry, utils
from ..telemetry import tracing as _tracing
from ..utils import nest
from . import serialization

# Process-wide wire metrics (docs/TELEMETRY.md).  Per-Rpc views stay on the
# connection objects (transport_stats/debug_info); the registry carries the
# same counters labeled by transport for exporters and cohort aggregation.
_REG = telemetry.get_registry()
_M_TX_BYTES = _REG.counter(
    "rpc_tx_bytes_total", "bytes sent on the wire (frame payloads)", ("transport",)
)
_M_RX_BYTES = _REG.counter(
    "rpc_rx_bytes_total", "bytes received on the wire", ("transport",)
)
_M_TX_FRAMES = _REG.counter("rpc_tx_frames_total", "frames sent", ("transport",))
_M_RX_FRAMES = _REG.counter("rpc_rx_frames_total", "frames received", ("transport",))
_M_RTT = _REG.histogram(
    "rpc_rtt_seconds", "request->response round trips (clean samples only)",
    ("transport",),
)
_M_PEER_LATENCY = _REG.gauge(
    "rpc_peer_latency_seconds",
    "per-peer-connection latency EMA (the bandit's input)",
    ("peer", "transport"),
)
_M_CALL_ERRORS = _REG.counter(
    "rpc_call_errors_total", "calls completed with an error", ("kind",)
)
_M_NACKS = _REG.counter(
    "rpc_nacks_recovered_total", "requests resent after a receiver NACK"
)
_M_CONNECTS = _REG.counter(
    "rpc_connections_total", "connections registered", ("transport", "direction")
)
_M_QUEUE_DEPTH = _REG.gauge(
    "rpc_queue_depth", "calls waiting in a define_queue", ("queue",)
)
_M_QUEUE_ITEMS = _REG.counter(
    "rpc_queue_items_total", "calls serviced through a define_queue", ("queue",)
)
_M_QUEUE_TAKES = _REG.counter(
    "rpc_queue_takes_total", "service takes (batches) from a define_queue", ("queue",)
)
_M_QUEUE_WAIT = _REG.histogram(
    "rpc_queue_wait_seconds", "enqueue to service start", ("queue",)
)

# Protocol signature; a peer greeting with a different signature is rejected
# (reference kSignature, src/rpc.cc:810). Bumped when wire behavior changes
# incompatibly (0002: keepalive ping/pong + activity-based teardown; 0003:
# max-(initiator_uid, dial_seq) duplicate-connection tie-break — mixed
# versions would deterministically keep DIFFERENT duplicates and flap;
# 0004: poke/ack/nack fast recovery frames; 0005: request header grew a
# 2-byte trace-context length + optional 24-byte trace block after the fn
# name — an 0004 peer would parse trace bytes as payload).
SIGNATURE = 0x6D6F6F5450550005

KIND_GREETING = 1
KIND_REQUEST = 2
KIND_RESPONSE = 3
KIND_ERROR = 4
KIND_KEEPALIVE = 5
# Fast recovery (reference poke/ack/nack, src/rpc.cc:2526-2703): after a
# short silence the sender POKEs ("do you have rid X?"); the receiver
# re-sends the cached response, ACKs ("executing"), or NACKs ("never saw
# it") — a NACK triggers an immediate resend, so a dropped frame recovers at
# RTT scale instead of blind-resend scale.
KIND_POKE = 6
KIND_ACK = 7
KIND_NACK = 8

_DEFAULT_TIMEOUT = 120.0
# Keepalive cadence (reference: keepalives after idle, teardown of
# unresponsive connections, src/rpc.cc:1625-1665). A connection that has
# received nothing for _CONN_DEAD seconds while we kept pinging it is torn
# down; explicit connections then auto-reconnect.
_KEEPALIVE_IDLE = 4.0
_KEEPALIVE_INTERVAL = 2.0
_CONN_DEAD = 16.0
# Fast-recovery cadence: poke a silent rid after _POKE_AFTER; blind-resend
# the full request only if nothing (ack/nack/response) came back for
# _RESEND_BLIND — the fallback for lost control frames.
_POKE_AFTER = 0.75
_RESEND_BLIND = 9.0
# Frames at least this large ride the memfd zero-copy path on ipc://
# connections between fd-passing-capable native peers.
_MEMFD_MIN = 1024 * 1024


class RpcError(RuntimeError):
    """Custom exception for Rpc errors (matches reference ``RpcError``)."""


class FrameTooLargeError(RpcError):
    """Payload exceeds the 4 GiB wire-frame limit (u32 length prefix).

    Permanent for a given payload: callers must NOT treat it as a dead
    connection (closing + resending would flap the link forever)."""


class Future:
    """Thread-safe future with asyncio interop, mirroring the reference's
    ``FutureWrapper`` (``src/moolib.cc:316-392``)."""

    __slots__ = ("_event", "_result", "_exc", "_callbacks", "_lock", "_cancelled")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List[Callable] = []
        self._lock = threading.Lock()
        self._cancelled = False

    # -- producer side ----------------------------------------------------
    def set_result(self, value) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._result = value
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def set_exception(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    # -- consumer side ----------------------------------------------------
    def result(self, timeout: Optional[float] = None):
        self.wait(timeout)
        if self._exc is not None:
            raise self._exc
        return self._result

    def wait(self, timeout: Optional[float] = None) -> None:
        if not self._event.wait(timeout):
            raise TimeoutError("Future timed out")

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        self._cancelled = True
        self.set_exception(RpcError("Future cancelled"))

    def exception(self) -> Optional[BaseException]:
        if self._event.is_set():
            return self._exc
        return None

    def add_done_callback(self, cb: Callable) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def __await__(self):
        loop = asyncio.get_event_loop()
        af = loop.create_future()

        def _done(self_, loop=loop, af=af):
            def _transfer():
                if af.cancelled():
                    return
                if self_._exc is not None:
                    af.set_exception(self_._exc)
                else:
                    af.set_result(self_._result)

            loop.call_soon_threadsafe(_transfer)

        self.add_done_callback(_done)
        return af.__await__()

    __iter__ = __await__


class RpcDeferredReturn:
    """Callable handed to deferred handlers; calling it sends the response."""

    __slots__ = ("_send", "_sent")

    def __init__(self, send: Callable[[Any, Optional[str]], None]):
        self._send = send
        self._sent = False

    def __call__(self, value=None) -> None:
        if self._sent:
            raise RpcError("RpcDeferredReturn called twice")
        self._sent = True
        self._send(value, None)

    def error(self, message: str) -> None:
        if self._sent:
            raise RpcError("RpcDeferredReturn called twice")
        self._sent = True
        self._send(None, message)


def _chunk_len(c) -> int:
    return c.nbytes if isinstance(c, memoryview) else len(c)


def _request_chunks(
    rid: int, fn_name: str, body: List[bytes], timeout_s: float, trace: bytes = b""
) -> List[bytes]:
    """Single source of truth for the request frame layout. The sender's
    call timeout travels with the request so the receiver can size its
    at-most-once dedup window to outlive every possible resend.  ``trace``
    is the encoded trace context (24 bytes when a trace is active, empty
    otherwise — untraced calls pay zero extra wire bytes beyond the length
    field)."""
    fnb = fn_name.encode()
    hdr = struct.pack(
        "<BQIHH",
        KIND_REQUEST,
        rid,
        min(int(timeout_s), 0xFFFFFFFF),
        len(fnb),
        len(trace),
    )
    return [hdr + fnb + trace] + body


def _trace_for_request():
    """Trace-context capture for one outgoing request.  Returns
    ``(wire_bytes, call_ctx, parent_ctx)``: a fresh child context whose
    span id becomes the ``rpc.call`` span (and the remote handler's
    parent), or ``(b"", None, None)`` when the calling thread has no
    active trace."""
    parent = _tracing.current_context()
    if parent is None:
        return b"", None, None
    call = parent.child()
    return _tracing.encode_context(call), call, parent


def _record_call_span(out: "_Outgoing", peers: Optional[int] = None) -> None:
    """Record the client-side ``rpc.call`` span when the response future
    resolves.  The span id matches what rode the wire, so the remote
    ``rpc.recv`` span's parent edge lands on it in a merged trace."""
    trace_id, span_id, parent_id = out.trace_parent
    args = {"peer": out.peer_name, "rid": out.rid}
    if peers is not None:
        args["peers"] = peers
    _tracing.get_tracer().record(
        f"rpc.call {out.fn_name}",
        out.t0_ns,
        time.perf_counter_ns() - out.t0_ns,
        trace_id=trace_id,
        span_id=span_id,
        parent_id=parent_id,
        args=args,
    )


# Shared no-op context manager: untraced requests skip span creation
# entirely (nullcontext is reusable and reentrant).
_NULL_CM = contextlib.nullcontext()


def _recv_span(fn_name: str, tctx, rid=None):
    """Child span for handler execution under a remote caller's context;
    a no-op when the request carried none."""
    if tctx is None:
        return _NULL_CM
    args = {} if rid is None else {"rid": rid}
    return _tracing.child_span(f"rpc.recv {fn_name}", tctx, **args)


def _record_resend_span(out: "_Outgoing", why: str) -> None:
    """Record a retry as a SIBLING of the rpc.call span (fresh span id,
    same parent) — resends stay visible in the trace without duplicating
    the call span's id.  Instant event (no meaningful duration)."""
    if out.trace_parent is None:
        return
    trace_id, _span_id, parent_id = out.trace_parent
    _tracing.get_tracer().record(
        f"rpc.resend {out.fn_name}",
        time.perf_counter_ns(),
        0,
        trace_id=trace_id,
        span_id=_tracing.new_span_id(),
        parent_id=parent_id,
        args={"peer": out.peer_name, "rid": out.rid, "why": why},
    )


def _local_addresses() -> List[str]:
    """Addresses to advertise for a wildcard listen: real interfaces first,
    loopback last (reference: deviceAddresses gathering for the greeting)."""
    import socket as _socket

    addrs: List[str] = []
    try:
        host = _socket.gethostname()
        for ip in _socket.gethostbyname_ex(host)[2]:
            if not ip.startswith("127.") and ip not in addrs:
                addrs.append(ip)
    except OSError:
        pass
    try:
        # UDP-connect trick: finds the IP of the default route interface.
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        if not ip.startswith("127.") and ip not in addrs:
            addrs.insert(0, ip)
    except OSError:
        pass
    addrs.append("127.0.0.1")
    return addrs


_BOOT_ID: Optional[str] = None


def _boot_id() -> str:
    """Machine identity for same-host detection (the reference's network key
    is the boot id too, ``src/transports/ipc.cc:280-315`` getNetworkKey).
    When the boot id is unreadable, fall back to a per-process random value:
    Rpcs in this process still match each other (genuinely same host), while
    cross-process peers never match — the upgrade quietly disables rather
    than treating two arbitrary machines as same-host."""
    global _BOOT_ID
    if _BOOT_ID is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                _BOOT_ID = f.read().strip()
        except OSError:
            _BOOT_ID = f"noboot-{utils.create_uid()}"
    return _BOOT_ID


def parse_address(addr: str) -> Tuple[str, Any]:
    """Parse "tcp://host:port", "ipc://path", "host:port", ":port"."""
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://") :]
    elif addr.startswith("ipc://"):
        return ("ipc", addr[len("ipc://") :])
    elif addr.startswith("shm://"):
        # The reference advertises a shared-memory transport; we map it onto a
        # unix socket in the abstract namespace-ish tmp path.
        return ("ipc", f"/tmp/moolib_tpu_shm_{addr[len('shm://'):]}")
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        raise RpcError(f"cannot parse address {addr!r}")
    return ("tcp", (host or "0.0.0.0", int(port)))


class _Connection:
    """One live stream (tcp or ipc) to a remote peer."""

    __slots__ = (
        "transport",
        "reader",
        "writer",
        "rpc",
        "peer_name",
        "peer_uid",
        "send_count",
        "recv_count",
        "bytes_out",
        "bytes_in",
        "latency",
        "bandit",
        "bandit_t",
        "created",
        "last_recv",
        "last_keepalive",
        "closed",
        "inbound",
        "initiator_uid",
        "conn_seq",
        "_explicit_addr",
        "_m_tx_bytes",
        "_m_rx_bytes",
        "_m_tx_frames",
        "_m_rx_frames",
        "_m_rtt",
        "_m_peer_lat",
    )

    def __init__(self, transport: str, reader, writer, inbound: bool = False):
        self.transport = transport
        self.reader = reader
        self.writer = writer
        self.inbound = inbound
        # Bind the registry children once (per-frame cost is one locked add).
        self._m_tx_bytes = _M_TX_BYTES.labels(transport=transport)
        self._m_rx_bytes = _M_RX_BYTES.labels(transport=transport)
        self._m_tx_frames = _M_TX_FRAMES.labels(transport=transport)
        self._m_rx_frames = _M_RX_FRAMES.labels(transport=transport)
        self._m_rtt = _M_RTT.labels(transport=transport)
        self._m_peer_lat = None  # bound on first RTT (peer name from greeting)
        _M_CONNECTS.inc(
            transport=transport, direction="inbound" if inbound else "outbound"
        )
        # Owning Rpc (set at dial/accept).  Gives the ``send_frame`` fault
        # seam the SENDER's identity, so a simulated network partition
        # (testing.faults.Partition) can drop frames by (sender, receiver)
        # pair even with many Rpcs in one process.
        self.rpc = None
        self.peer_name: Optional[str] = None
        self.peer_uid: Optional[str] = None
        self.send_count = 0
        self.recv_count = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.latency: Optional[float] = None  # EMA seconds
        # Bandit value in [-1, 1] (reference banditValue, src/rpc.cc:640-716):
        # nudged up when this transport currently has the peer's best latency,
        # down otherwise, with time decay; transport choice is a softmax over
        # exp(bandit * 4), so the loser still gets occasional probe traffic
        # and can win back after a regime change.
        self.bandit = 0.0
        self.bandit_t = 0.0
        self.created = time.monotonic()
        self.last_recv = time.monotonic()
        self.last_keepalive = 0.0
        # Duplicate-connection tie-break identity: who dialed, and that
        # side's dial sequence number (set at dial for outbound, from the
        # greeting for inbound). Both ends keep the max — deterministic.
        self.initiator_uid: Optional[str] = None
        self.conn_seq = 0
        self.closed = False
        self._explicit_addr: Optional[str] = None

    def send_frame(self, chunks: List[bytes]) -> None:
        # Coalesce the frame into ONE buffer and issue a single write().
        # Feeding many chunks into the transport triggers CPython 3.12's
        # sendmsg multi-buffer accounting bug (gh: "pop from an empty deque"
        # in _adjust_leftover_buffer), which corrupts the stream under load.
        # One memcpy per frame also beats the sendmsg path on throughput.
        total = sum(_chunk_len(c) for c in chunks)
        if total > 0x7FFFFFFF:
            # Bit 31 of the length prefix is the memfd-frame flag (native
            # transport); both backends cap regular frames at 2 GiB - 1.
            raise FrameTooLargeError(f"frame of {total} bytes exceeds the 2 GiB limit")
        buf = bytearray(4 + total)
        struct.pack_into("<I", buf, 0, total)
        off = 4
        for c in chunks:
            if isinstance(c, memoryview) and c.ndim != 1:
                c = c.cast("B")
            n = _chunk_len(c)
            buf[off : off + n] = c
            off += n
        self.writer.write(buf)
        self.send_count += 1
        self.bytes_out += total
        self._m_tx_frames.inc()
        self._m_tx_bytes.inc(total)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.writer.close()
            except Exception:
                pass


class _NativeConnection(_Connection):
    """A stream owned by the native epoll engine (``native/transport.cc``).

    Same duck type as ``_Connection``; frames go out through the C engine
    (which adds the 4-byte length prefix and batches writes with writev),
    and arrive via engine callbacks instead of an asyncio read loop.
    """

    __slots__ = ("net", "conn_id", "rx_seen", "tx_seen")

    def __init__(self, net, conn_id: int, transport: str, rpc, inbound: bool = False):
        super().__init__(transport, None, None, inbound=inbound)
        self.net = net
        self.conn_id = conn_id
        self.rpc = rpc
        self.rx_seen = -1  # engine byte counters at last liveness check
        self.tx_seen = -1

    def send_frame(self, chunks: List[bytes]) -> None:
        total = sum(_chunk_len(c) for c in chunks)
        if total > 0x7FFFFFFF:
            raise FrameTooLargeError("frame exceeds the 2 GiB limit")
        # Same-host zero-copy: large frames to an fd-passing-capable peer on
        # a unix socket ride an anonymous memfd + SCM_RIGHTS — the payload
        # never crosses the socket buffers (VERDICT round-1 ask #8;
        # reference groundwork src/memory/memfd.cc + sendFd).
        if total >= _MEMFD_MIN and self.transport == "ipc":
            peer = self.rpc._peers.get(self.peer_name) if self.peer_name else None
            if peer is not None and peer.fdp_ok:
                if self.net.send_memfd(self.conn_id, chunks):
                    self.send_count += 1
                    self.bytes_out += total
                    self._m_tx_frames.inc()
                    self._m_tx_bytes.inc(total)
                    return
        if not self.net.send_iov(self.conn_id, chunks):
            raise RpcError("native send failed (engine destroyed or conn gone)")
        self.send_count += 1
        self.bytes_out += total
        self._m_tx_frames.inc()
        self._m_tx_bytes.inc(total)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.net.close_conn(self.conn_id)
            # Explicit closes get no engine callback; detach ourselves.
            self.rpc._native_forget(self.conn_id)


class _Peer:
    __slots__ = (
        "name",
        "uid",
        "connections",
        "addresses",
        "pending",
        "recent",
        "executing",
        "find_inflight",
        "native_ok",
        "fdp_ok",
        "upgrade_attempts",
    )

    def __init__(self, name: str):
        self.name = name
        self.uid: Optional[str] = None
        # ipc addresses we dialed for same-host transport upgrade -> when.
        self.upgrade_attempts: Dict[str, float] = {}
        # Whether the peer can decode the native codec (negotiated in the
        # greeting; until/unless true we send pickle-codec payloads).
        self.native_ok = False
        # Whether the peer's transport engine can receive SCM_RIGHTS memfd
        # frames (native engine only; negotiated in the greeting).
        self.fdp_ok = False
        self.connections: Dict[str, _Connection] = {}
        self.addresses: List[str] = []
        self.pending: List["_Outgoing"] = []  # waiting for a connection
        self.recent: Dict[int, Tuple[float, List[bytes]]] = {}  # rid -> (ts, resp chunks)
        self.executing: set = set()
        self.find_inflight = False

    def best_connection(self, order: List[str], big: bool = False) -> Optional[_Connection]:
        """Pick the transport for one message: softmax over per-connection
        bandit values (reference banditSend, ``src/rpc.cc:640-716``) —
        mostly-exploit with a sliver of exploration so a transport that went
        bad (or got one unlucky sample) keeps producing fresh latency data.

        ``big`` payloads (at/above the memfd zero-copy threshold) pick a live
        ipc connection outright: the latency bandit can't see throughput, and
        a same-host unix stream with SCM_RIGHTS memfd frames always beats
        loopback TCP on bytes/sec — size-aware selection is the upgrade over
        the reference's latency-only bandit.
        """
        if big:
            c = self.connections.get("ipc")
            if c is not None and not c.closed:
                return c
        conns = [c for c in self.connections.values() if not c.closed]
        if not conns:
            return None
        if len(conns) == 1:
            return conns[0]
        # Unmeasured connections start at the configured preference order
        # (ipc beats tcp locally) via a small bandit prior.
        def weight(c: _Connection):
            prior = 0.0
            if c.latency is None and c.transport in order:
                prior = 0.25 * (len(order) - order.index(c.transport)) / len(order)
            return math.exp((c.bandit + prior) * 4.0)

        ws = [weight(c) for c in conns]
        t = random.random() * sum(ws)
        for c, w in zip(conns, ws):
            t -= w
            if t <= 0:
                return c
        return conns[-1]

    def note_latency(self, conn: _Connection, rtt: float) -> None:
        """Fold one RTT sample into the connection's EMA and re-score the
        bandit values of every live connection to this peer (the analogue of
        the reference's addLatency, ``src/rpc.cc:2448-2486``)."""
        conn.latency = rtt if conn.latency is None else conn.latency * 0.9 + rtt * 0.1
        conn._m_rtt.observe(rtt)
        if conn.peer_name:
            # The EMA the bandit scores on, readable through the registry;
            # debug_info stays a view.  Bound lazily: the peer name only
            # exists after the greeting.
            if conn._m_peer_lat is None:
                conn._m_peer_lat = _M_PEER_LATENCY.labels(
                    peer=conn.peer_name, transport=conn.transport
                )
            conn._m_peer_lat.set(conn.latency)
        measured = [
            c
            for c in self.connections.values()
            if not c.closed and c.latency is not None
        ]
        if len(measured) < 2:
            return
        best = min(measured, key=lambda c: c.latency)
        now = time.monotonic()
        for c in measured:
            dt = now - (c.bandit_t or now)
            c.bandit *= 0.9375 ** min(dt, 60.0)
            c.bandit += 0.125 if c is best else -0.125
            c.bandit = max(-1.0, min(1.0, c.bandit))
            c.bandit_t = now


class _Outgoing:
    __slots__ = (
        "rid",
        "peer_name",
        "fn_name",
        "chunks",
        "chunks_portable",
        "payload_obj",
        "future",
        "deadline",
        "sent_at",
        "timeout_s",
        "resent",
        "parked",
        "last_probe",
        "acked_at",
        "peers_pending",
        "trace",
        "trace_parent",
        "t0_ns",
    )

    def __init__(self, rid, peer_name, fn_name, chunks, payload_obj, future, deadline):
        self.rid = rid
        self.peer_name = peer_name
        self.fn_name = fn_name
        self.chunks = chunks  # native-or-python encoding (sender's default)
        self.chunks_portable = None  # lazily built pickle-codec encoding
        self.payload_obj = payload_obj  # retained for portable re-encode
        self.future = future
        self.deadline = deadline
        self.sent_at = time.monotonic()
        self.timeout_s = _DEFAULT_TIMEOUT
        self.resent = False  # RTT samples from resent requests are ambiguous
        self.parked = False  # already waiting in peer.pending
        self.last_probe = 0.0  # last POKE sent for this rid
        self.acked_at = 0.0  # receiver confirmed it is executing
        # Broadcast requests (async_broadcast): the peers that have not
        # responded yet.  One rid + one serialized frame fan out to all of
        # them (receiver dedup is per (peer, rid), so the shared rid is
        # unambiguous); None for ordinary single-peer requests.
        self.peers_pending: Optional[set] = None
        # Distributed-tracing state: the encoded context bytes riding the
        # wire (threaded through portable re-encodes), the (trace_id,
        # span_id, parent_id) of the rpc.call span to record at completion,
        # and the send-time perf_counter_ns.  All None/b"" when untraced.
        self.trace = b""
        self.trace_parent = None
        self.t0_ns = 0


class _FnDef:
    __slots__ = ("name", "fn", "kind", "batch_size", "dynamic", "batch_state", "inline")

    def __init__(self, name, fn, kind, batch_size=None, dynamic=False, inline=False):
        self.name = name
        self.fn = fn
        self.kind = kind  # "plain" | "deferred" | "queue" | "batched"
        self.batch_size = batch_size
        self.dynamic = dynamic
        self.batch_state: List = []  # collected calls for kind=="batched"
        # Inline handlers run synchronously on the receiving IO thread with
        # BORROWED argument arrays (zero-copy views over the receive buffer,
        # valid only for the duration of the call) — the hot path of the
        # bucketed gradient combine.  See Rpc.define.
        self.inline = inline


_ADOPT = threading.local()
_ADOPT.ctx = None

# True while testing.faults.FrameFaults wraps the send_frame seam: the
# memfd-multicast broadcast fast path (which bypasses per-connection
# send_frame) steps aside so every frame stays visible to fault injection.
frame_seam_hooked = False


def adopt_current_frame():
    """Take ownership of the memfd mapping behind the frame currently being
    delivered on THIS thread (valid only inside an inline RPC handler on the
    native transport).  Returns a uint8 numpy array over the mapping — alive
    for the array's own lifetime, munmap'd by a GC finalizer — or None when
    the current frame is not an adoptable mapping (small copied frames, TCP,
    asyncio transport).  This is the zero-copy receive terminus of the
    flat-bucket data plane: the allreduce share result stays in the shared
    memfd pages instead of being copied out."""
    ctx = getattr(_ADOPT, "ctx", None)
    if ctx is None:
        return None
    net, frame = ctx
    if net is None:
        return None
    arr = net.adopt_frame(frame)
    if arr is not None:
        # One adoption per frame: further calls (other arrays in the same
        # payload) must go through the first adopter.
        _ADOPT.ctx = (None, None)
    return arr


_live_rpcs: "weakref.WeakSet[Rpc]" = weakref.WeakSet()


def _close_live_rpcs():
    """atexit: close every Rpc the user leaked (reference leak tracking +
    atexit cleanup, src/moolib.cc:127-183). Engines must stop BEFORE the
    interpreter finalizes — a C++ epoll thread calling back into a
    finalizing interpreter aborts."""
    for rpc in list(_live_rpcs):
        try:
            rpc.close()
        except Exception:  # noqa: BLE001 - best effort at shutdown
            pass


atexit.register(_close_live_rpcs)


class Queue:
    """Incoming-call queue created by ``Rpc.define_queue``.

    Awaiting (or iterating) yields ``(return_callback, args, kwargs)``; with
    ``batch_size`` set, args/kwargs arrive stacked along dim 0 across callers
    and the return callback unstacks the response back to each caller
    (reference ``QueueWrapper`` ``src/moolib.cc:426-576,1122-1178``).
    """

    def __init__(
        self,
        batch_size: Optional[int] = None,
        dynamic_batching: bool = False,
        name: str = "anon",
    ):
        self._items: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._waiters: collections.deque = collections.deque()  # (loop, asyncio.Future)
        self._batch_size = batch_size
        self._dynamic = dynamic_batching
        # Cumulative service-quality counters (serve_bench reads these to
        # make the batching crossover visible: how full batches run and how
        # long calls sat queued before service).  The same numbers feed the
        # process registry labeled by queue name — stats() stays the
        # per-instance view, the registry the exported one.
        self._stats = {
            "items": 0, "takes": 0, "wait_s_sum": 0.0, "wait_s_max": 0.0,
            "depth_max": 0,
        }
        self._m_depth = _M_QUEUE_DEPTH.labels(queue=name)
        self._m_items = _M_QUEUE_ITEMS.labels(queue=name)
        self._m_takes = _M_QUEUE_TAKES.labels(queue=name)
        self._m_wait = _M_QUEUE_WAIT.labels(queue=name)

    # producer (rpc engine or user's enqueue) ------------------------------
    def enqueue(self, return_callback, args=None, kwargs=None) -> None:
        with self._lock:
            self._items.append((return_callback, args or (), kwargs or {}, time.monotonic()))
            self._stats["depth_max"] = max(self._stats["depth_max"], len(self._items))
            # inc/dec (not set): instances sharing a queue name — two peers
            # in one process defining the same fn — then SUM to a meaningful
            # process-wide depth instead of last-writer-wins clobbering.
            self._m_depth.inc()
            self._maybe_wake_locked()

    def _maybe_wake_locked(self) -> None:
        need = 1 if (self._batch_size is None or self._dynamic) else self._batch_size
        while self._waiters and len(self._items) >= need:
            loop, af = self._waiters.popleft()
            batch = self._take_locked()
            loop.call_soon_threadsafe(_set_async_result, af, batch)

    def _account_locked(self, calls) -> list:
        now = time.monotonic()
        s = self._stats
        s["takes"] += 1
        s["items"] += len(calls)
        self._m_takes.inc()
        self._m_items.inc(len(calls))
        self._m_depth.dec(len(calls))
        for c in calls:
            wait = now - c[3]
            s["wait_s_sum"] += wait
            s["wait_s_max"] = max(s["wait_s_max"], wait)
            self._m_wait.observe(wait)
        return [c[:3] for c in calls]

    def _take_locked(self):
        if self._batch_size is None:
            return self._account_locked([self._items.popleft()])[0]
        n = len(self._items) if self._dynamic else self._batch_size
        n = min(n, self._batch_size, len(self._items))
        calls = self._account_locked([self._items.popleft() for _ in range(n)])
        return _batch_calls(calls)

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def stats(self) -> Dict[str, float]:
        """Cumulative queue service counters: ``items`` serviced, service
        ``takes`` (batches — average batch fill is items/takes), queue
        ``wait_s_sum``/``wait_s_max`` (enqueue to service start), and
        high-water ``depth_max``.  Thin per-instance view; the same numbers
        export through the registry as ``rpc_queue_*{queue=<name>}``
        (docs/TELEMETRY.md)."""
        with self._lock:
            return dict(self._stats)

    def __await__(self):
        loop = asyncio.get_event_loop()
        af = loop.create_future()
        with self._lock:
            need = 1 if (self._batch_size is None or self._dynamic) else self._batch_size
            if len(self._items) >= need:
                batch = self._take_locked()
                af.set_result(batch)
            else:
                self._waiters.append((loop, af))
        return af.__await__()

    __iter__ = __await__


def _set_async_result(af, value):
    if not af.cancelled():
        af.set_result(value)


def _batch_calls(calls):
    """Stack N collected calls into one batched call + unstacking return cb."""
    rets = [c[0] for c in calls]
    argss = [c[1] for c in calls]
    kwargss = [c[2] for c in calls]
    n = len(calls)
    if n == 1:
        return calls[0]
    batched_args = tuple(nest.stack([a for a in argss], dim=0)) if argss[0] else ()
    batched_kwargs = nest.stack([k for k in kwargss], dim=0) if kwargss[0] else {}

    def return_callback(value):
        parts = nest.unstack(value, dim=0)
        for ret, part in zip(rets, parts):
            ret(part)

    def error(message: str) -> None:
        # Fail every caller stacked into this batch (mirrors
        # RpcDeferredReturn.error so queue consumers can error uniformly).
        for ret in rets:
            ret.error(message)

    return_callback.error = error
    # Per-caller returns, row-aligned with the stacked batch: consumers that
    # need sub-batch blast-radius control (serving's unbatched retry of a
    # poisoned batch) answer callers individually instead of failing all.
    return_callback.rets = rets
    return (return_callback, batched_args, batched_kwargs)


class Rpc:
    """An RPC peer. See module docstring for the design.

    Concurrency model (mirrors the reference's poll-thread + fine-grained
    locking rather than pure loop confinement): ``_state`` guards all engine
    state (peers, outgoing, connections). With the native transport, frames
    are processed directly on the C++ epoll thread under ``_state`` — no
    cross-thread hop on the hot path. Futures complete *outside* ``_state``
    (their done-callbacks take caller locks). The asyncio fallback keeps all
    socket writes on the loop thread (asyncio transports are not
    thread-safe), so there sends marshal onto the loop as before.
    """

    def __init__(self):
        self._name = utils.create_uid()
        self._uid = utils.create_uid()
        self._timeout = _DEFAULT_TIMEOUT
        # Which remote failures are reported back to the caller (reference
        # ExceptionMode None/DeserializationOnly/All, src/rpc.h:201-205).
        # Default "all": handler exceptions return as RpcError with the full
        # remote traceback — richer than the reference's default.
        self._exception_mode = "all"
        self._state = threading.RLock()
        self._transport_order = ["ipc", "tcp"]
        self._functions: Dict[str, _FnDef] = {}
        self._peers: Dict[str, _Peer] = {}
        self._conns: List[_Connection] = []
        self._servers: List = []
        self._listen_addrs: List[str] = []
        self._explicit: List[str] = []
        self._rid = itertools.count(1)
        self._dial_seq = itertools.count(1)
        self._outgoing: Dict[int, _Outgoing] = {}
        self._nacks_recovered = 0  # requests resent on receiver NACK
        self._closed = False
        self._functions["__moolib_find_peer"] = _FnDef(
            "__moolib_find_peer", self._find_peer_handler, "plain"
        )
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=utils.get_max_threads() or min(32, (os.cpu_count() or 4))
        )
        # Warm the native codec here (user thread): first use compiles with
        # g++; doing it lazily would block the IO event loop mid-greeting.
        serialization.native_available()
        # Native epoll IO engine (C++), with asyncio fallback. The engine owns
        # the sockets; protocol state stays on the asyncio loop thread.
        self._net = None
        self._native_conns: Dict[int, _NativeConnection] = {}
        self._connect_reqs: Dict[int, Any] = {}
        self._connect_req_counter = itertools.count(1)
        if os.environ.get("MOOLIB_TPU_NATIVE_TRANSPORT", "1") != "0":
            try:
                from ..native.transport import NativeNet

                self._net = NativeNet(
                    self._net_on_accept,
                    self._net_on_frame,
                    self._net_on_close,
                    self._net_on_connect,
                )
            except Exception:  # noqa: BLE001 - fall back to asyncio sockets
                self._net = None
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop_main, name="moolib-rpc", daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()
        _live_rpcs.add(self)

    # ------------------------------------------------------------------ loop
    def _loop_main(self):
        asyncio.set_event_loop(self._loop)
        self._loop.call_soon(self._started.set)
        self._loop.create_task(self._timeout_task())
        try:
            self._loop.run_forever()
        finally:
            try:
                pending = asyncio.all_tasks(self._loop)
                for t in pending:
                    t.cancel()
                self._loop.run_until_complete(asyncio.gather(*pending, return_exceptions=True))
            except Exception:
                pass
            self._loop.close()

    def _call_in_loop(self, fn, *args):
        if threading.current_thread() is self._thread:
            fn(*args)
        else:
            try:
                self._loop.call_soon_threadsafe(fn, *args)
            except RuntimeError:
                pass  # loop shut down

    def _spawn(self, coro_factory):
        """Schedule a coroutine on the engine loop from any thread."""
        if threading.current_thread() is self._thread:
            self._loop.create_task(coro_factory())
        else:
            try:
                self._loop.call_soon_threadsafe(
                    lambda: self._loop.create_task(coro_factory())
                )
            except RuntimeError:
                pass

    # ------------------------------------------------------------------ api
    def set_name(self, name: str) -> None:
        self._name = str(name)

    def get_name(self) -> str:
        return self._name

    def set_timeout(self, seconds: float) -> None:
        self._timeout = float(seconds)

    def set_transports(self, transports: List[str]) -> None:
        self._transport_order = list(transports)

    def set_exception_mode(self, mode: str) -> None:
        """Choose which remote failures travel back to callers (reference
        ``Rpc::setExceptionMode``, ``src/rpc.h:201-205``):

        - ``"none"``: nothing is reported; a failing call times out on the
          caller while the host logs the error.
        - ``"deserialization"``: only argument-deserialization errors are
          reported (the reference's default); handler exceptions are logged
          host-side and the call times out.
        - ``"all"`` (default): handler exceptions are reported with the full
          remote traceback text.

        Unknown-function errors are protocol-level and always reported.
        Swallowed failures leave the request uncached, so a sender resend
        may re-execute the handler — these modes are debugging tools, not a
        consistency mechanism.
        """
        if mode not in ("none", "deserialization", "all"):
            raise ValueError(f"exception mode must be none|deserialization|all, got {mode!r}")
        self._exception_mode = mode

    def listen(self, address: str) -> None:
        # A bare ":port" listens on every default transport (reference
        # Rpc::listen, src/rpc.cc:3102-3136): all TCP interfaces plus an
        # auto-pathed unix listener, so same-host peers can transport-upgrade
        # to ipc/memfd no matter which address they dialed.
        if address.startswith(":") and not any(
            a.startswith("ipc://") for a in self._listen_addrs
        ):
            self.listen(f"ipc:///tmp/moolib_tpu_{self._uid}.sock")
        kind, target = parse_address(address)
        if self._net is not None:
            if kind == "tcp":
                host, port = target
                native_host = host
                if host not in ("", "0.0.0.0"):
                    # The native engine binds numeric IPv4 only; resolve
                    # hostnames here (user thread, listen is rare).
                    import socket as _socket

                    try:
                        _socket.inet_pton(_socket.AF_INET, host)
                    except OSError:
                        native_host = _socket.gethostbyname(host)
                actual_port = self._net.listen_tcp(native_host, port)
                self._advertise_tcp(native_host, actual_port)
            else:
                self._net.listen_unix(target)
                with self._state:
                    self._listen_addrs.append(f"ipc://{target}")
            return
        fut = concurrent.futures.Future()

        async def _do():
            try:
                if kind == "tcp":
                    host, port = target
                    server = await asyncio.start_server(
                        lambda r, w: self._on_accept("tcp", r, w), host, port
                    )
                    sock = server.sockets[0]
                    actual_port = sock.getsockname()[1]
                    self._advertise_tcp(host, actual_port)
                else:
                    path = target
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    server = await asyncio.start_unix_server(
                        lambda r, w: self._on_accept("ipc", r, w), path
                    )
                    self._listen_addrs.append(f"ipc://{path}")
                self._servers.append(server)
                fut.set_result(None)
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        asyncio.run_coroutine_threadsafe(_do(), self._loop)
        fut.result(10)

    def _advertise_tcp(self, host: str, actual_port: int) -> None:
        with self._state:
            if host in ("0.0.0.0", ""):
                # Advertise every reachable interface address so cross-host
                # gossip discovery works (not just loopback).
                for adv in _local_addresses():
                    self._listen_addrs.append(f"tcp://{adv}:{actual_port}")
            else:
                self._listen_addrs.append(f"tcp://{host}:{actual_port}")

    def connect(self, address: str) -> None:
        """Connect to an address; the connection is kept alive (reconnects)."""
        self._explicit.append(address)
        self._call_in_loop(lambda: self._loop.create_task(self._reconnect_task(address)))

    def peer_name_at(self, address: str) -> Optional[str]:
        """Name of the connected peer that advertises ``address`` among its
        greeting listen addresses, or None if no greeting from there has
        completed yet.  Calls route by peer NAME; this is how a client
        holding a LIST of broker *addresses* (broker-HA failover) resolves
        each one to the name it must actually call."""
        try:
            kind, target = parse_address(address)
        except Exception:
            return None
        if kind == "ipc":
            want = {f"ipc://{target}"}
        else:
            host, port = target
            hosts = {host}
            if host in ("0.0.0.0", "", "localhost"):
                hosts.add("127.0.0.1")
            else:
                try:
                    import socket as _socket

                    hosts.add(_socket.gethostbyname(host))
                except OSError:
                    pass
            want = {f"tcp://{h}:{port}" for h in hosts}
        with self._state:
            for p in self._peers.values():
                if any(a in want for a in p.addresses):
                    return p.name
        return None

    def define(self, name: str, fn: Callable, batch_size: Optional[int] = None,
               inline: bool = False) -> None:
        """Register ``fn`` as a callable RPC endpoint.

        ``inline=True`` is a hot-path opt-in for engine-internal handlers
        (the Group's allreduce combine): the handler runs synchronously on
        the receiving IO thread and its numpy array arguments are ZERO-COPY
        read-only views over the receive buffer, valid only for the duration
        of the call.  The handler must be fast, must not block, and must
        copy anything it retains past the return.  Regular (non-inline)
        handlers keep the copying deserialization and run on the thread
        pool — the safe default for user code.
        """
        if name in self._functions:
            raise RpcError(f"function {name!r} already defined")
        if inline and batch_size:
            raise RpcError("inline handlers cannot be batched")
        kind = "batched" if batch_size else "plain"
        self._functions[name] = _FnDef(name, fn, kind, batch_size, inline=inline)

    def define_deferred(self, name: str, fn: Callable) -> None:
        if name in self._functions:
            raise RpcError(f"function {name!r} already defined")
        self._functions[name] = _FnDef(name, fn, "deferred")

    def define_queue(
        self, name: str, batch_size: Optional[int] = None, dynamic_batching: bool = False
    ) -> Queue:
        if name in self._functions:
            raise RpcError(f"function {name!r} already defined")
        q = Queue(batch_size, dynamic_batching, name=name)
        fd = _FnDef(name, q, "queue", batch_size, dynamic_batching)
        self._functions[name] = fd
        return q

    def undefine(self, name: str) -> None:
        self._functions.pop(name, None)

    def async_(self, peer_name: str, fn_name: str, *args, **kwargs) -> Future:
        future = Future()
        self._send_request(peer_name, fn_name, args, kwargs, future)
        return future

    def async_callback(self, peer_name: str, fn_name: str, callback: Callable, *args, **kwargs):
        future = Future()

        def _done(f: Future):
            exc = f.exception()
            if exc is not None:
                callback(None, exc)
            else:
                callback(f._result, None)

        future.add_done_callback(_done)
        self._send_request(peer_name, fn_name, args, kwargs, future)

    def sync(self, peer_name: str, fn_name: str, *args, **kwargs):
        return self.async_(peer_name, fn_name, *args, **kwargs).result()

    def async_broadcast(self, peer_names: List[str], fn_name: str, *args, **kwargs) -> Future:
        """Send ONE request to several peers: the payload serializes once,
        and when every target is a same-host fd-passing peer the frame is
        written into a single memfd multicast to all of them (the payload
        bytes leave this process exactly once — the allreduce share-down's
        fast path).  All targets share one rid (receiver dedup is per peer,
        so this is unambiguous) and the returned future resolves to None
        once every peer has responded; per-peer results are discarded.
        Reliability is the standard poke/resend machinery, applied per
        pending peer."""
        future = Future()
        if not peer_names:
            future.set_result(None)
            return future
        try:
            sp = serialization.serialize((args, kwargs))
            body = serialization.pack(sp)
        except Exception as e:  # noqa: BLE001
            future.set_exception(RpcError(f"serialization error: {e}"))
            return future
        rid = next(self._rid)
        tb, call_ctx, parent_ctx = _trace_for_request()
        chunks = _request_chunks(rid, fn_name, body, self._timeout, tb)
        deadline = time.monotonic() + self._timeout
        out = _Outgoing(rid, peer_names[0], fn_name, chunks, (args, kwargs), future, deadline)
        out.timeout_s = self._timeout
        out.peers_pending = set(peer_names)
        if call_ctx is not None:
            out.trace = tb
            out.trace_parent = (call_ctx.trace_id, call_ctx.span_id, parent_ctx.span_id)
            out.t0_ns = time.perf_counter_ns()

        def _done(fut: Future):
            with self._state:
                self._outgoing.pop(rid, None)
            if out.trace_parent is not None:
                _record_call_span(out, peers=len(peer_names))

        future.add_done_callback(_done)
        with self._state:
            if not future.done():
                self._outgoing[rid] = out
                self._try_send(out)
        return future

    def _try_send_broadcast(self, out: _Outgoing):
        """Send (or resend) a broadcast request to every pending peer.
        Caller holds self._state.  The memfd-multicast fast path covers the
        peers reachable over same-host fd-passing ipc connections; everyone
        else gets an ordinary per-connection send of the same chunks."""
        fast: List[Tuple[_Peer, _NativeConnection]] = []
        slow: List[Tuple[_Peer, _Connection]] = []
        big = sum(_chunk_len(c) for c in out.chunks) >= _MEMFD_MIN
        for name in list(out.peers_pending or ()):
            peer = self._peers.get(name)
            conn = peer.best_connection(self._transport_order, big=big) if peer else None
            if conn is None:
                if peer is None:
                    peer = self._peers.setdefault(name, _Peer(name))
                self._spawn(lambda peer=peer: self._find_peer(peer))
                continue
            if (
                big
                and not frame_seam_hooked
                and self._net is not None
                and isinstance(conn, _NativeConnection)
                and conn.transport == "ipc"
                and peer.native_ok
                and peer.fdp_ok
            ):
                fast.append((peer, conn))
            else:
                slow.append((peer, conn))
        if fast:
            ids = [c.conn_id for _, c in fast]
            sent = self._net.send_memfd_multi(ids, out.chunks)
            total = sum(_chunk_len(c) for c in out.chunks)
            if sent == len(ids):
                for _, c in fast:
                    c.send_count += 1
                    c.bytes_out += total
                    c._m_tx_frames.inc()
                    c._m_tx_bytes.inc(total)
            else:
                # Unknown subset failed: resend individually; receivers
                # dedup duplicate rids.
                slow.extend(fast)
        for peer, conn in slow:
            try:
                conn.send_frame(self._chunks_for(peer, out))
            except Exception:
                conn.close()
        out.sent_at = time.monotonic()

    def debug_info(self) -> str:
        with self._state:
            return self._debug_info_locked()

    def _debug_info_locked(self) -> str:
        lines = [f"Rpc {self._name} (uid {self._uid}) listen={self._listen_addrs}"]
        for p in self._peers.values():
            lines.append(f"  peer {p.name} uid={p.uid} addrs={p.addresses}")
            for t, c in p.connections.items():
                lat = f"{c.latency*1e6:.0f}us" if c.latency is not None else "?"
                lines.append(
                    f"    {t}: sent={c.send_count} recv={c.recv_count}"
                    f" tx={c.bytes_out} rx={c.bytes_in} latency={lat}"
                    f" bandit={c.bandit:+.2f}"
                    f" age={time.monotonic()-c.created:.1f}s closed={c.closed}"
                )
        lines.append(
            f"  outstanding={len(self._outgoing)} nacks_recovered={self._nacks_recovered}"
            f" functions={list(self._functions)}"
        )
        return "\n".join(lines)

    def multicast_ready(self, peer_names: List[str]) -> bool:
        """True when every named peer is reachable over a live same-host
        fd-passing ipc connection — i.e. ``async_broadcast`` of a large
        frame will take the write-once memfd multicast path.  The allreduce
        share-down uses this to pick root-star (payload written once for the
        whole cohort) over tree forwarding."""
        if self._net is None:
            return False
        ready = True
        hunt: List[_Peer] = []
        with self._state:
            for name in peer_names:
                p = self._peers.get(name)
                if p is None or not any(
                    not c.closed for c in p.connections.values()
                ):
                    # Not even connected yet (tree traffic never needed it):
                    # start discovery so later rounds can upgrade to the
                    # multicast star; this round stays on the tree.
                    p = self._peers.setdefault(name, _Peer(name))
                    hunt.append(p)
                    ready = False
                    continue
                if not (p.native_ok and p.fdp_ok):
                    ready = False
                    continue
                c = p.connections.get("ipc")
                if c is None or c.closed or not isinstance(c, _NativeConnection):
                    ready = False
        for p in hunt:
            self._spawn(lambda p=p: self._find_peer(p))
        return ready

    def transport_stats(self) -> Dict[str, int]:
        """Aggregate wire counters across every live/dead-but-tracked
        connection: {"tx_bytes", "rx_bytes", "tx_frames", "rx_frames"}.
        The allreduce benchmark uses the per-peer spread of these to show
        the chunked ring's even load (vs the tree root's 2x hotspot).
        Thin per-Rpc view; the process-wide equivalents export through the
        registry as ``rpc_{tx,rx}_{bytes,frames}_total{transport=...}``."""
        with self._state:
            tx = rx = txf = rxf = 0
            for c in self._conns:
                tx += c.bytes_out
                rx += c.bytes_in
                txf += c.send_count
                rxf += c.recv_count
            return {"tx_bytes": tx, "rx_bytes": rx, "tx_frames": txf, "rx_frames": rxf}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True

        def _shutdown():
            for c in list(self._conns):
                c.close()
            for s in self._servers:
                s.close()
            self._loop.stop()

        try:
            self._loop.call_soon_threadsafe(_shutdown)
            self._thread.join(timeout=5)
        except Exception:
            pass
        if self._net is not None:
            # After the loop stops nothing sends; joining the epoll thread
            # here guarantees no callback fires into a dead Rpc. (ctypes
            # releases the GIL during the call, so an in-flight callback can
            # finish.)
            try:
                self._net.destroy()
            except Exception:
                pass
        self._executor.shutdown(wait=False)

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass

    # --------------------------------------------------------- send path
    def _send_request(self, peer_name, fn_name, args, kwargs, future: Future):
        try:
            sp = serialization.serialize((args, kwargs))
            body = serialization.pack(sp)
        except Exception as e:  # noqa: BLE001
            future.set_exception(RpcError(f"serialization error: {e}"))
            return
        rid = next(self._rid)
        tb, call_ctx, parent_ctx = _trace_for_request()
        chunks = _request_chunks(rid, fn_name, body, self._timeout, tb)
        deadline = time.monotonic() + self._timeout
        out = _Outgoing(rid, peer_name, fn_name, chunks, (args, kwargs), future, deadline)
        out.timeout_s = self._timeout
        if call_ctx is not None:
            out.trace = tb
            out.trace_parent = (call_ctx.trace_id, call_ctx.span_id, parent_ctx.span_id)
            out.t0_ns = time.perf_counter_ns()

        def _done(fut: Future):
            # Completed (incl. user cancel): drop the resend buffer promptly.
            with self._state:
                self._outgoing.pop(rid, None)
            if out.trace_parent is not None:
                _record_call_span(out)

        future.add_done_callback(_done)

        if self._net is not None:
            # Native engine: sends are thread-safe; register + send inline.
            with self._state:
                if not future.done():
                    self._outgoing[rid] = out
                    self._try_send(out)
            return

        def _do():
            with self._state:
                if not future.done():
                    self._outgoing[rid] = out
                    self._try_send(out)

        self._call_in_loop(_do)

    def _send_poke(self, out: _Outgoing):
        # Caller holds self._state. Pokes are best-effort: if there is no
        # live connection, the greeting-time resend path owns recovery.
        names = out.peers_pending if out.peers_pending is not None else (out.peer_name,)
        for name in list(names):
            peer = self._peers.get(name)
            conn = peer.best_connection(self._transport_order) if peer else None
            if conn is None:
                continue
            try:
                conn.send_frame([struct.pack("<BQ", KIND_POKE, out.rid)])
            except Exception:
                conn.close()

    def _try_send(self, out: _Outgoing):
        # Caller holds self._state.
        if out.peers_pending is not None:
            self._try_send_broadcast(out)
            return
        peer = self._peers.get(out.peer_name)
        big = sum(_chunk_len(c) for c in out.chunks) >= _MEMFD_MIN
        conn = peer.best_connection(self._transport_order, big=big) if peer else None
        if conn is not None:
            try:
                conn.send_frame(self._chunks_for(peer, out))
                out.sent_at = time.monotonic()
                return
            except FrameTooLargeError as e:
                # Permanent for this payload — fail the call; closing the
                # (healthy) connection and resending would flap forever.
                # Complete off-thread: we hold _state here.
                with self._state:
                    self._outgoing.pop(out.rid, None)
                self._executor.submit(out.future.set_exception, RpcError(str(e)))
                return
            except Exception:
                conn.close()
        # No usable connection: park on the peer (once) and go find it.
        if peer is None:
            peer = self._peers.setdefault(out.peer_name, _Peer(out.peer_name))
        if not out.parked:
            out.parked = True
            peer.pending.append(out)
        self._spawn(lambda peer=peer: self._find_peer(peer))

    def _chunks_for(self, peer: _Peer, out: _Outgoing) -> List[bytes]:
        """Codec negotiation: if the peer can't decode native payloads,
        re-encode this request with the portable pickle codec."""
        if peer.native_ok or not serialization.native_available():
            return out.chunks
        if out.chunks_portable is None:
            sp = serialization._py_serialize(out.payload_obj)
            out.chunks_portable = _request_chunks(
                out.rid, out.fn_name, serialization.pack(sp), out.timeout_s, out.trace
            )
        return out.chunks_portable

    async def _find_peer(self, peer: _Peer):
        if peer.find_inflight:
            return
        peer.find_inflight = True
        try:
            # Try known addresses first, then gossip through connected peers
            # (reference reqLookingForPeer, src/rpc.cc:2332-2433).
            with self._state:
                addrs = list(peer.addresses)
            for addr in addrs:
                if any(not c.closed for c in peer.connections.values()):
                    return  # a dial (ours or another task's) just won
                if await self._connect_once(addr):
                    return
            with self._state:
                others = [p for p in self._peers.values() if p is not peer and p.connections]
            if others:
                sample = random.sample(others, min(len(others), max(2, int(len(others) ** 0.5))))
                for other in sample:
                    f = self.async_(other.name, "__moolib_find_peer", peer.name)

                    def _found(fut, peer=peer):
                        try:
                            addrs = fut.result(0)
                        except Exception:
                            return
                        if addrs:
                            with self._state:
                                for a in addrs:
                                    if a not in peer.addresses:
                                        peer.addresses.append(a)
                            self._spawn(lambda peer=peer: self._retry_connect(peer))

                    f.add_done_callback(_found)
        finally:
            peer.find_inflight = False

    async def _retry_connect(self, peer: _Peer):
        for addr in list(peer.addresses):
            if any(not c.closed for c in peer.connections.values()):
                return
            await self._connect_once(addr)

    async def _connect_once(self, address: str, explicit_addr: Optional[str] = None) -> bool:
        if self._net is not None:
            return await self._native_connect(address, explicit_addr)
        try:
            kind, target = parse_address(address)
            if kind == "tcp":
                host, port = target
                reader, writer = await asyncio.open_connection(host, port)
            else:
                reader, writer = await asyncio.open_unix_connection(target)
        except Exception:
            return False
        conn = _Connection(kind, reader, writer)
        conn.rpc = self
        conn.initiator_uid = self._uid
        conn.conn_seq = next(self._dial_seq)
        if explicit_addr is not None:
            # Tag so the reconnect task can see whether its address is live.
            conn._explicit_addr = explicit_addr
        self._conns.append(conn)
        self._send_greeting(conn)
        self._loop.create_task(self._read_loop(conn))
        return True

    async def _reconnect_task(self, address: str):
        backoff = 0.25
        while not self._closed:
            have = any(
                not c.closed
                for c in self._conns
                if getattr(c, "_explicit_addr", None) == address
            )
            if not have:
                ok = await self._connect_once(address, explicit_addr=address)
                backoff = 0.5 if ok else min(backoff * 2, 4.0)
            await asyncio.sleep(backoff)

    # ------------------------------------------------- native engine plumbing
    async def _native_connect(self, address: str, explicit_addr: Optional[str]) -> bool:
        try:
            kind, target = parse_address(address)
        except Exception:
            return False
        if kind == "tcp":
            host, port = target
            host = await self._resolve_host(host)
            if host is None:
                return False
        req_id = next(self._connect_req_counter)
        af = self._loop.create_future()
        with self._state:
            self._connect_reqs[req_id] = (af, kind, explicit_addr)
        if kind == "tcp":
            self._net.connect_tcp(req_id, host, port)
        else:
            self._net.connect_unix(req_id, target)
        return await af

    async def _resolve_host(self, host: str) -> Optional[str]:
        """Resolve a hostname to a numeric address off the IO threads (the
        native engine only dials numeric addresses — blocking getaddrinfo on
        its epoll thread would stall every connection)."""
        import socket as _socket

        try:
            _socket.inet_pton(_socket.AF_INET, host)
            return host  # already numeric
        except OSError:
            pass
        try:
            infos = await self._loop.getaddrinfo(host, None, type=_socket.SOCK_STREAM)
        except OSError:
            return None
        for family, _, _, _, sockaddr in infos:
            if family == _socket.AF_INET:
                return sockaddr[0]
        return infos[0][4][0] if infos else None

    # The _net_on_* callbacks run on the C++ epoll thread and process frames
    # right there under _state — no cross-thread hop on the hot path (the
    # reference handles messages on its poll thread the same way). The frame
    # is a ZERO-COPY view into the engine's receive buffer, valid only until
    # the callback returns: every deserialize path copies array/bytes leaves
    # during materialization, and nothing may retain `frame` (or slices of
    # it) past the callback.
    def _net_on_accept(self, conn_id: int, transport: str):
        with self._state:
            conn = _NativeConnection(self._net, conn_id, transport, self, inbound=True)
            self._native_conns[conn_id] = conn
            self._conns.append(conn)
            self._send_greeting(conn)

    def _net_on_frame(self, conn_id: int, frame: bytes):
        with self._state:
            conn = self._native_conns.get(conn_id)
            if conn is None or conn.closed:
                return
            conn.recv_count += 1
            conn.bytes_in += len(frame)
            conn.last_recv = time.monotonic()
            conn._m_rx_frames.inc()
            conn._m_rx_bytes.inc(len(frame))
        # Publish the frame for adopt_current_frame(): an inline handler may
        # take ownership of a memfd frame's mapping (zero-copy receive into
        # a long-lived buffer) while the callback is on this stack.
        prev = getattr(_ADOPT, "ctx", None)
        _ADOPT.ctx = (self._net, frame)
        try:
            self._on_frame(conn, frame)
        finally:
            _ADOPT.ctx = prev

    def _net_on_close(self, conn_id: int):
        with self._state:
            conn = self._native_conns.pop(conn_id, None)
            if conn is None:
                return
            conn.closed = True
            self._detach_conn(conn)

    def _native_forget(self, conn_id: int):
        with self._state:
            conn = self._native_conns.pop(conn_id, None)
            if conn is not None:
                self._detach_conn(conn)

    def _net_on_connect(self, req_id: int, conn_id: int):
        # Register the connection synchronously: the peer's greeting can race
        # through the epoll thread the moment the connect resolves, and it
        # must find the connection registered.
        with self._state:
            entry = self._connect_reqs.pop(req_id, None)
            if entry is None:
                if conn_id >= 0:
                    self._net.close_conn(conn_id)
                return
            af, kind, explicit_addr = entry
            ok = conn_id >= 0
            if ok:
                conn = _NativeConnection(self._net, conn_id, kind, self)
                conn.initiator_uid = self._uid
                conn.conn_seq = next(self._dial_seq)
                if explicit_addr is not None:
                    conn._explicit_addr = explicit_addr
                self._native_conns[conn_id] = conn
                self._conns.append(conn)
                self._send_greeting(conn)
        # The awaiting coroutine lives on the loop: complete its future there.
        self._call_in_loop(_set_async_result, af, ok)

    def _send_greeting(self, conn: _Connection):
        # Greetings always use the portable pickle codec: they must parse
        # before codec support has been negotiated.
        greeting = serialization.dumps_portable(
            {
                "sig": SIGNATURE,
                "name": self._name,
                "uid": self._uid,
                "addrs": list(self._listen_addrs),
                "host": _boot_id(),
                "native": serialization.native_available(),
                # fd-passing capability: our engine can receive SCM_RIGHTS
                # memfd frames (native transport only).
                "fdp": self._net is not None,
                # Dial sequence of this connection if WE initiated it (the
                # acceptor learns it for the duplicate tie-break).
                "seq": conn.conn_seq if not conn.inbound else 0,
            }
        )
        conn.send_frame([struct.pack("<B", KIND_GREETING), greeting])

    # --------------------------------------------------------- receive path
    def _on_accept(self, transport: str, reader, writer):
        conn = _Connection(transport, reader, writer, inbound=True)
        conn.rpc = self
        self._conns.append(conn)
        self._send_greeting(conn)
        self._loop.create_task(self._read_loop(conn))

    async def _read_loop(self, conn: _Connection):
        try:
            while not self._closed:
                hdr = await conn.reader.readexactly(4)
                (length,) = struct.unpack("<I", hdr)
                if length <= 1 << 20:
                    frame = await conn.reader.readexactly(length)
                else:
                    # Chunked read of large frames so last_recv reflects
                    # byte-level progress (keepalive teardown must not kill
                    # a link mid-way through a big transfer).
                    buf = bytearray(length)
                    got = 0
                    while got < length:
                        piece = await conn.reader.readexactly(min(1 << 20, length - got))
                        buf[got : got + len(piece)] = piece
                        got += len(piece)
                        conn.last_recv = time.monotonic()
                    frame = bytes(buf)
                conn.recv_count += 1
                conn.bytes_in += length
                conn.last_recv = time.monotonic()
                conn._m_rx_frames.inc()
                conn._m_rx_bytes.inc(length)
                self._on_frame(conn, frame)
        except (asyncio.IncompleteReadError, ConnectionError, OSError, asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001
            utils.log_error("rpc read loop error: %s", traceback.format_exc())
        finally:
            conn.close()
            self._detach_conn(conn)

    def _detach_conn(self, conn: _Connection):
        with self._state:
            if conn in self._conns:
                self._conns.remove(conn)
            if conn.peer_name is not None:
                peer = self._peers.get(conn.peer_name)
                if peer is not None and peer.connections.get(conn.transport) is conn:
                    del peer.connections[conn.transport]

    def _on_frame(self, conn: _Connection, frame: bytes):
        kind = frame[0]
        if kind == KIND_GREETING:
            self._on_greeting(conn, frame)
        elif kind == KIND_REQUEST:
            self._on_request(conn, frame)
        elif kind in (KIND_RESPONSE, KIND_ERROR):
            self._on_response(conn, frame, kind == KIND_ERROR)
        elif kind == KIND_KEEPALIVE:
            # Ping (flag 0) wants a pong (flag 1) so the *sender's* last_recv
            # refreshes too; pongs are not echoed (no ping-pong storm).
            if len(frame) < 2 or frame[1] == 0:
                try:
                    conn.send_frame([struct.pack("<BB", KIND_KEEPALIVE, 1)])
                except Exception:
                    conn.close()
        elif kind == KIND_POKE:
            self._on_poke(conn, frame)
        elif kind == KIND_ACK:
            self._on_ack(frame)
        elif kind == KIND_NACK:
            self._on_nack(frame)
        else:
            utils.log_error("rpc: unknown frame kind %d", kind)

    def _on_poke(self, conn: _Connection, frame: bytes):
        """Receiver side of fast recovery: the sender suspects loss on rid.
        Cached response → resend it; executing → ACK; unknown → NACK (the
        request frame died — sender resends immediately)."""
        (rid,) = struct.unpack_from("<Q", frame, 1)
        reply = None
        with self._state:
            peer = self._peers.get(conn.peer_name) if conn.peer_name else None
            if peer is not None:
                cached = peer.recent.get(rid)
                if cached is not None:
                    reply = cached[1]
                elif rid in peer.executing:
                    reply = [struct.pack("<BQ", KIND_ACK, rid)]
                else:
                    reply = [struct.pack("<BQ", KIND_NACK, rid)]
        if reply is not None:
            try:
                conn.send_frame(reply)
            except Exception:
                conn.close()

    def _on_ack(self, frame: bytes):
        (rid,) = struct.unpack_from("<Q", frame, 1)
        with self._state:
            out = self._outgoing.get(rid)
            if out is not None:
                out.acked_at = time.monotonic()

    def _on_nack(self, frame: bytes):
        (rid,) = struct.unpack_from("<Q", frame, 1)
        with self._state:
            out = self._outgoing.get(rid)
            if out is not None:
                self._nacks_recovered += 1
                _M_NACKS.inc()
                out.resent = True
                _record_resend_span(out, "nack")
                self._try_send(out)

    def _on_greeting(self, conn: _Connection, frame: bytes):
        info = serialization.loads(memoryview(frame)[1:])
        if info.get("sig") != SIGNATURE:
            utils.log_error("rpc: protocol signature mismatch, closing connection")
            conn.close()
            return
        name, uid = info["name"], info["uid"]
        if uid == self._uid:
            conn.close()  # self-connection (reference src/rpc.cc:2209-2224)
            return
        with self._state:
            self._on_greeting_locked(conn, info, name, uid)

    def _on_greeting_locked(self, conn: _Connection, info, name: str, uid: str):
        conn.peer_name = name
        conn.peer_uid = uid
        peer = self._peers.setdefault(name, _Peer(name))
        if peer.uid is not None and peer.uid != uid:
            # Same name, new incarnation (peer restarted): its rid space
            # restarts too, so the previous incarnation's dedup cache must go.
            peer.recent.clear()
            peer.executing.clear()
        peer.uid = uid
        peer.native_ok = bool(info.get("native", False))
        peer.fdp_ok = bool(info.get("fdp", False))
        for a in info.get("addrs", []):
            if a not in peer.addresses:
                peer.addresses.append(a)
        if conn.inbound:
            conn.initiator_uid = uid
            conn.conn_seq = int(info.get("seq", 0))
        old = peer.connections.get(conn.transport)
        if old is not None and old is not conn and not old.closed:
            # Duplicate-connection tie-break. Duplicates happen two ways:
            # simultaneous connect (each side dialed the other) and redundant
            # dials from one side (reconnect task racing discovery before the
            # first greeting lands). Keep the max (initiator_uid, dial_seq) —
            # both ends compute the same winner regardless of the order the
            # greetings arrived in, so they never keep different connections
            # (which would look like the peer closing our healthy link).
            new_key = (conn.initiator_uid or "", conn.conn_seq)
            old_key = (old.initiator_uid or "", old.conn_seq)
            if old_key >= new_key:
                conn.close()
                return
            old.close()
        peer.connections[conn.transport] = conn
        # Flush anything parked while the peer was unknown, and resend every
        # outstanding request addressed to this peer — receiver-side dedup
        # makes the resend idempotent (at-most-once execution).
        pending, peer.pending = peer.pending, []
        seen = set()
        for out in pending:
            out.parked = False
            if out.rid in self._outgoing and out.rid not in seen:
                seen.add(out.rid)
                self._try_send(out)
        for out in list(self._outgoing.values()):
            if out.rid in seen:
                continue
            if out.peers_pending is not None:
                # Broadcast: resend when THIS peer is still pending (the
                # single peer_name field only names the first target).
                if name in out.peers_pending:
                    self._try_send(out)
            elif out.peer_name == name:
                self._try_send(out)
        self._maybe_upgrade_transport(peer, info)

    def _maybe_upgrade_transport(self, peer: _Peer, info: dict) -> None:
        """Same-host transport upgrade (the reference's automatic transport
        selection, ``README.md:17-19`` / ``src/rpc.cc:640-716``): when a peer
        reached over TCP advertises an ipc:// listener on this machine
        (boot-id match), dial it too.  The bandit then has both transports
        and big frames take the unix/memfd zero-copy path outright.  Caller
        holds ``self._state``.  Only the uid-smaller side dials, so the pair
        doesn't create rival duplicate connections to tie-break."""
        if info.get("host") != _boot_id():
            return
        if peer.uid is not None and self._uid >= peer.uid:
            return
        ipc = peer.connections.get("ipc")
        if ipc is not None and not ipc.closed:
            return
        now = time.monotonic()
        for a in info.get("addrs", []):
            if not a.startswith("ipc://"):
                continue
            if now - peer.upgrade_attempts.get(a, -1e9) < 10.0:
                return  # a recent dial is in flight / just failed
            peer.upgrade_attempts[a] = now
            self._spawn(lambda a=a: self._connect_once(a))
            return

    def _on_request(self, conn: _Connection, frame: bytes):
        rid, sender_timeout, fnlen, tclen = struct.unpack_from("<QIHH", frame, 1)
        off = 1 + 8 + 4 + 2 + 2
        fn_name = bytes(frame[off : off + fnlen]).decode()
        off += fnlen
        # Remote trace context (0005): present only when the caller had an
        # active trace.  The handler runs under a child span of the caller's
        # rpc.call span — the cross-process edge trace_merge stitches on.
        tctx = _tracing.decode_context(bytes(frame[off : off + tclen])) if tclen else None
        off += tclen
        # At-most-once window must outlive every possible resend by this
        # sender: size it from the *sender's* call timeout, not ours.
        dedup_ttl = max(2.0 * sender_timeout, 120.0)
        with self._state:
            peer = self._peers.get(conn.peer_name) if conn.peer_name else None
            if peer is not None:
                cached = peer.recent.get(rid)
                if cached is not None:
                    try:
                        conn.send_frame(cached[1])
                    except Exception:
                        conn.close()
                    return
                if rid in peer.executing:
                    return  # duplicate while executing; response will go out
                peer.executing.add(rid)

        def respond(value, error: Optional[str], stage: str = "handler"):
            # Serialize outside the state lock (can be large); then publish
            # the dedup entry and send under it.
            if error is not None and not self._report_error(stage):
                # Swallowed by the exception mode: log host-side, free the
                # in-flight dedup slot (no response will ever go out), and
                # let the caller time out — reference None/DeserializationOnly
                # behavior (src/rpc.h:271-293).
                utils.log_error(
                    "rpc %s: %s error swallowed (exception_mode=%s): %s",
                    self._name, stage, self._exception_mode, error,
                )
                with self._state:
                    if peer is not None:
                        peer.executing.discard(rid)
                return
            ser_fn = (
                serialization.serialize
                if (peer is None or peer.native_ok)
                else serialization._py_serialize
            )
            try:
                if error is not None:
                    body = serialization.pack(ser_fn(error))
                    chunks = [struct.pack("<BQ", KIND_ERROR, rid)] + body
                else:
                    body = serialization.pack(ser_fn(value))
                    chunks = [struct.pack("<BQ", KIND_RESPONSE, rid)] + body
            except Exception as e:  # noqa: BLE001
                # A response that cannot serialize is a handler-stage failure:
                # it obeys the same exception-mode gate as a raising handler.
                if not self._report_error("handler"):
                    utils.log_error(
                        "rpc %s: response serialization error swallowed "
                        "(exception_mode=%s): %s",
                        self._name, self._exception_mode, e,
                    )
                    with self._state:
                        if peer is not None:
                            peer.executing.discard(rid)
                    return
                body = serialization.pack(
                    serialization._py_serialize(f"response serialization error: {e}")
                )
                chunks = [struct.pack("<BQ", KIND_ERROR, rid)] + body

            def _send():
                with self._state:
                    if peer is not None:
                        peer.executing.discard(rid)
                        peer.recent[rid] = (time.monotonic(), chunks, dedup_ttl)
                    # Respond over the best currently-alive connection to the
                    # peer; fall back to the one the request came in on.
                    big = sum(_chunk_len(c) for c in chunks) >= _MEMFD_MIN
                    target = (
                        peer.best_connection(self._transport_order, big=big)
                        if peer else None
                    )
                    if target is None or target.closed:
                        target = conn
                    try:
                        target.send_frame(chunks)
                    except FrameTooLargeError:
                        # Drop the response (caller times out); the link is
                        # healthy and must not be closed.
                        utils.log_error(
                            "rpc: response for rid %s exceeds the frame limit", rid
                        )
                    except Exception:
                        target.close()

            if self._net is not None:
                _send()  # native sends are thread-safe
            else:
                self._call_in_loop(_send)

        fdef = self._functions.get(fn_name)
        if fdef is None:
            respond(
                None,
                f"function {fn_name!r} is not defined on peer {self._name!r}",
                stage="protocol",
            )
            return
        if fdef.inline and fdef.kind == "plain":
            # Inline hot path: borrowed (zero-copy) argument arrays, handler
            # run right here on the receiving thread — while the frame's
            # receive buffer is still valid (native transport frames die
            # when this callback returns).  The handler contract (fast,
            # non-blocking, copy-on-retention) lives in Rpc.define.
            try:
                sp = serialization.unpack(frame, off)
                args, kwargs = serialization.deserialize(sp, borrow=True)
            except Exception as e:  # noqa: BLE001
                respond(None, f"argument deserialization error: {e}", stage="deserialization")
                return
            try:
                with _recv_span(fn_name, tctx, rid):
                    respond(fdef.fn(*args, **kwargs), None)
            except Exception:  # noqa: BLE001
                respond(None, f"exception in {fdef.name!r}: {traceback.format_exc()}")
            return
        try:
            sp = serialization.unpack(frame, off)
            args, kwargs = serialization.deserialize(sp)
        except Exception as e:  # noqa: BLE001
            respond(None, f"argument deserialization error: {e}", stage="deserialization")
            return
        self._dispatch(fdef, args, kwargs, respond, tctx=tctx, rid=rid)

    def _report_error(self, stage: str) -> bool:
        """Is this error stage reported to the caller under the current mode?"""
        if stage == "protocol":
            return True
        if stage == "deserialization":
            return self._exception_mode in ("deserialization", "all")
        return self._exception_mode == "all"

    def _dispatch(self, fdef: _FnDef, args, kwargs, respond, tctx=None, rid=None):
        # tctx: the caller's trace context decoded off the frame.  Each
        # execution path runs the handler under an rpc.recv child span, so
        # handler-internal span()/async_ calls chain beneath it — including
        # onward RPCs, which re-encode the context for the next hop.
        if fdef.kind == "queue":
            # The span covers the enqueue (service time is the queue's own
            # business); the Queue can capture current_context() here to
            # reattach at take time.
            with _recv_span(fdef.name, tctx, rid):
                fdef.fn.enqueue(RpcDeferredReturn(respond), args, kwargs)
            return
        if fdef.kind == "deferred":
            ret = RpcDeferredReturn(respond)

            def run_deferred():
                try:
                    with _recv_span(fdef.name, tctx, rid):
                        fdef.fn(ret, *args, **kwargs)
                except Exception:  # noqa: BLE001
                    if not ret._sent:
                        ret.error(f"exception in {fdef.name!r}: {traceback.format_exc()}")

            self._executor.submit(run_deferred)
            return
        if fdef.kind == "batched":
            fdef.batch_state.append((respond, args, kwargs))
            if len(fdef.batch_state) >= fdef.batch_size:
                calls, fdef.batch_state = fdef.batch_state, []
                success_calls = [
                    ((lambda v, r=r: r(v, None)), a, k) for (r, a, k) in calls
                ]
                ret_cb, bargs, bkwargs = _batch_calls(success_calls)

                def run_batched():
                    try:
                        # The batch executes once for many callers; it runs
                        # under the flush-triggering caller's context.
                        with _recv_span(fdef.name, tctx, rid):
                            ret_cb(fdef.fn(*bargs, **bkwargs))
                    except Exception:  # noqa: BLE001
                        msg = f"exception in {fdef.name!r}: {traceback.format_exc()}"
                        for r, _, _ in calls:
                            r(None, msg)

                self._executor.submit(run_batched)
            return

        # plain
        if asyncio.iscoroutinefunction(fdef.fn):
            async def run_async():
                try:
                    with _recv_span(fdef.name, tctx, rid):
                        value = await fdef.fn(*args, **kwargs)
                    respond(value, None)
                except Exception:  # noqa: BLE001
                    respond(None, f"exception in {fdef.name!r}: {traceback.format_exc()}")

            # May be reached from the epoll thread (native transport):
            # _spawn marshals task creation onto the loop thread.
            self._spawn(run_async)
            return

        def run_plain():
            try:
                with _recv_span(fdef.name, tctx, rid):
                    value = fdef.fn(*args, **kwargs)
                respond(value, None)
            except Exception:  # noqa: BLE001
                respond(None, f"exception in {fdef.name!r}: {traceback.format_exc()}")

        self._executor.submit(run_plain)

    def _on_response(self, conn: _Connection, frame: bytes, is_error: bool):
        (rid,) = struct.unpack_from("<Q", frame, 1)
        with self._state:
            out = self._outgoing.get(rid)
            if out is None:
                return  # late/duplicate response
            if out.peers_pending is not None:
                # Broadcast: track per-peer completion; per-peer results are
                # discarded (fire-and-forget semantics with reliability).
                if conn.peer_name is not None:
                    out.peers_pending.discard(conn.peer_name)
                if out.peers_pending:
                    return
                self._outgoing.pop(rid, None)
                done_broadcast = out
            else:
                done_broadcast = None
                self._outgoing.pop(rid, None)
        if done_broadcast is not None:
            done_broadcast.future.set_result(None)
            return
        with self._state:
            if not out.resent:
                # Resent requests give ambiguous RTTs (which send answered?)
                rtt = time.monotonic() - out.sent_at
                peer = self._peers.get(conn.peer_name) if conn.peer_name else None
                if peer is not None:
                    peer.note_latency(conn, rtt)
                else:
                    conn.latency = (
                        rtt if conn.latency is None else conn.latency * 0.9 + rtt * 0.1
                    )
        # Deserialize + complete outside the lock: payloads can be large and
        # future done-callbacks take caller locks.
        try:
            value = serialization.deserialize(serialization.unpack(frame, 9))
        except Exception as e:  # noqa: BLE001
            _M_CALL_ERRORS.inc(kind="deserialization")
            out.future.set_exception(RpcError(f"response deserialization error: {e}"))
            return
        if is_error:
            _M_CALL_ERRORS.inc(kind="remote")
            out.future.set_exception(RpcError(str(value)))
        else:
            out.future.set_result(value)

    # --------------------------------------------------------- housekeeping
    async def _timeout_task(self):
        while not self._closed:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            with self._state:
                expired = [o for o in self._outgoing.values() if now >= o.deadline]
                for out in expired:
                    self._outgoing.pop(out.rid, None)
            # Complete outside the lock (done-callbacks take caller locks).
            for out in expired:
                _M_CALL_ERRORS.inc(kind="timeout")
                out.future.set_exception(
                    RpcError(f"Call ({out.peer_name}::{out.fn_name}) timed out")
                )
            hunts = []
            with self._state:
                # Fast recovery (reference poke/ack/nack, src/rpc.cc:2526-2703):
                # after _POKE_AFTER of silence on a rid, send a tiny POKE; a
                # NACK resends immediately (RTT-scale recovery), an ACK means
                # the handler is still running, a cached response is re-sent
                # by the receiver. The blind full resend remains as a fallback
                # for the case where the poke/nack frames themselves died.
                for out in list(self._outgoing.values()):
                    if now - out.sent_at > _RESEND_BLIND:
                        out.resent = True  # RTT no longer a clean sample
                        _record_resend_span(out, "blind")
                        self._try_send(out)
                        out.sent_at = now
                        continue
                    last = max(out.sent_at, out.last_probe, out.acked_at)
                    if now - last > _POKE_AFTER:
                        out.last_probe = now
                        self._send_poke(out)
                # Prune dead entries from pending queues (their futures
                # already timed out); park flags reset so nothing leaks
                # against a peer that never comes back.
                for peer in self._peers.values():
                    if peer.pending:
                        peer.pending = [
                            o for o in peer.pending if o.rid in self._outgoing
                        ]
                # Dedup entries carry their own TTL (derived from each
                # sender's call timeout at request time).
                now2 = time.monotonic()
                for peer in self._peers.values():
                    peer.recent = {
                        rid: v for rid, v in peer.recent.items() if now2 - v[0] < v[2]
                    }
                    # Keep hunting for peers with parked requests (a closed
                    # conn pending detach does not count as connected).
                    if peer.pending and not any(
                        not c.closed for c in peer.connections.values()
                    ):
                        hunts.append(peer)
                # Keepalives + unresponsive-connection teardown (reference
                # timeoutConnections, src/rpc.cc:1625-1665): ping idle
                # connections; a link that stays silent while pinged is dead
                # (no RST on a silently dropped path) — close it so explicit
                # connections reconnect and requests fail over.
                for conn in list(self._conns):
                    if conn.closed:
                        continue
                    if isinstance(conn, _NativeConnection):
                        # Byte-level liveness: a link mid-way through a huge
                        # frame (no frame completion, but bytes moving) is
                        # alive — don't tear it down. Inbound bytes are
                        # definitive; outbound "progress" counts only when
                        # substantial (a dead socket still absorbs small
                        # writes — like our pings — into the kernel buffer).
                        rx = conn.net.conn_rx(conn.conn_id)
                        tx = conn.net.conn_tx(conn.conn_id)
                        if rx != conn.rx_seen or (
                            conn.tx_seen >= 0 and tx - conn.tx_seen >= 262144
                        ):
                            conn.last_recv = now2
                        conn.rx_seen = rx
                        conn.tx_seen = tx
                    idle = now2 - conn.last_recv
                    if idle > _CONN_DEAD:
                        utils.log_verbose(
                            "rpc: closing unresponsive %s connection to %s",
                            conn.transport,
                            conn.peer_name,
                        )
                        conn.close()
                        self._detach_conn(conn)
                    elif idle > _KEEPALIVE_IDLE and now2 - conn.last_keepalive > _KEEPALIVE_INTERVAL:
                        conn.last_keepalive = now2
                        try:
                            conn.send_frame([struct.pack("<BB", KIND_KEEPALIVE, 0)])
                        except Exception:
                            conn.close()
                            self._detach_conn(conn)
            for peer in hunts:
                self._loop.create_task(self._find_peer(peer))

    def _find_peer_handler(self, target: str):
        peer = self._peers.get(target)
        if peer is None:
            return []
        return list(peer.addresses)
