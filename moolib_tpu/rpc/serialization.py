"""RPC payload serialization with out-of-band array buffers.

Counterpart of the reference's serializer stack (``src/serialization.h:1-461``,
``src/pythonserialization.h:43-423``, ``src/tensor.h:152-165``): python objects
are encoded with a tag-based fast path falling back to pickle, and tensors ride
*out of band* — only dtype/shape metadata goes in the payload stream while the
raw bytes are appended as separate buffers (the reference's
``x.addTensor(v, x.tell())`` side channel), so the transport can scatter-gather
them without copies.

The TPU-native twist: leaves may be ``jax.Array``. On the wire they stage
through host memory (``np.asarray``) — the analogue of the reference's
pinned-CPU staging for CUDA tensors (``src/accumulator.cc:859-873``) — and are
tagged so the receiver rematerializes a ``jax.Array`` (committed to the default
device) rather than a numpy array.
"""

from __future__ import annotations

import io
import pickle
import struct
from dataclasses import dataclass, field
from typing import Any, List, Sequence

import numpy as np

try:  # bfloat16 & friends come from ml_dtypes (always present with jax)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    ml_dtypes = None

_JAX = None


def _jax():
    global _JAX
    if _JAX is None:
        import jax

        _JAX = jax
    return _JAX


def _is_jax_array(x) -> bool:
    jax = _jax()
    return isinstance(x, jax.Array)


@dataclass
class ArrayRef:
    """Metadata for one out-of-band array buffer."""

    dtype: str
    shape: tuple
    kind: str  # "np" | "jax"
    data: Any = None  # bytes-like (only set on the wire side)


@dataclass
class SerializedPayload:
    payload: bytes = b""
    arrays: List[ArrayRef] = field(default_factory=list)

    def nbytes(self) -> int:
        return len(self.payload) + sum(
            a.data.nbytes if isinstance(a.data, memoryview) else len(a.data)
            for a in self.arrays
        )


class _Pickler(pickle.Pickler):
    """Pickler that diverts array leaves into the out-of-band table."""

    def __init__(self, file, arrays: List[ArrayRef]):
        super().__init__(file, protocol=pickle.HIGHEST_PROTOCOL)
        self._arrays = arrays

    def persistent_id(self, obj):
        # np.asarray(order="C") forces contiguity like ascontiguousarray but
        # WITHOUT its documented at-least-1d promotion: a 0-d loss scalar
        # must come back 0-d, not shape (1,) (caught by the hypothesis
        # round-trip sweep in tests/test_serialization_props.py).
        # Structured dtypes (dtype.names) pickle inline: the ArrayRef wire
        # format encodes dtype by NAME, which cannot express field layouts.
        if (
            isinstance(obj, np.ndarray)
            and obj.dtype != object
            and obj.dtype.names is None
        ):
            arr = np.asarray(obj, order="C")
            self._arrays.append(ArrayRef(_dtype_tag(arr.dtype), arr.shape, "np", _raw_data(arr)))
            return ("__array__", len(self._arrays) - 1)
        if _is_jax_array(obj):
            host = np.asarray(obj, order="C")
            self._arrays.append(ArrayRef(_dtype_tag(host.dtype), host.shape, "jax", _raw_data(host)))
            return ("__array__", len(self._arrays) - 1)
        if isinstance(obj, (np.generic,)):
            # 0-dim numpy scalars pickle fine inline; keep them in-band.
            return None
        return None


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, arrays: Sequence[ArrayRef], borrow: bool = False):
        super().__init__(file)
        self._arrays = arrays
        self._borrow = borrow

    def persistent_load(self, pid):
        tag, idx = pid
        if tag != "__array__":
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
        ref = self._arrays[idx]
        arr = _materialize(ref, borrow=self._borrow)
        return arr


def _raw_data(arr: np.ndarray):
    """Contiguous raw bytes of an array; extension dtypes (bfloat16, fp8 from
    ml_dtypes) don't implement the buffer protocol, so view through uint8 —
    via a 1-d reshape, because numpy refuses itemsize-changing views of 0-d
    arrays (the shape travels separately in the ArrayRef)."""
    try:
        return arr.data
    except (ValueError, BufferError):
        return arr.reshape(-1).view(np.uint8).data


def _dtype_tag(dt: np.dtype) -> str:
    """Wire tag for a dtype: the typestr when it round-trips (lossless for
    byte order and str/bytes/void widths — dtype.NAME is not: '>i4' names
    as 'int32' and '<U3' as 'str96'), else the name, which resolves
    extension dtypes (bfloat16, fp8) via ml_dtypes on decode."""
    s = dt.str
    try:
        if np.dtype(s) == dt:
            return s
    except TypeError:
        pass
    return dt.name


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        if ml_dtypes is not None:
            return np.dtype(getattr(ml_dtypes, name))
        raise


def _materialize(ref: ArrayRef, borrow: bool = False):
    arr = np.frombuffer(ref.data, dtype=_np_dtype(ref.dtype)).reshape(ref.shape)
    if ref.kind == "jax":
        import jax.numpy as jnp

        # Copy off the (transient) receive buffer before device_put: jax can
        # zero-copy alias host numpy buffers and keeps only the array object
        # alive, not the buffer beneath a frombuffer view.  borrow never
        # applies to jax leaves — device_put must own its memory.
        return jnp.asarray(arr.copy())
    if borrow:
        # Zero-copy: a read-only view straight over the receive buffer,
        # valid only as long as that buffer is (for RPC frames: the duration
        # of the handler call).  Callers opting in own the lifetime problem;
        # anything retained must be copied first.
        return arr
    # np.frombuffer gives a read-only view over the receive buffer; copy so
    # callers can mutate (the receive buffer is also about to be recycled).
    return arr.copy()


@dataclass
class NativePayload:
    """Payload produced by the native C++ codec (moolib_tpu.native): header
    bytes + a list of contiguous ndarrays referenced out of band."""

    payload: bytes
    np_arrays: List[Any]

    def nbytes(self) -> int:
        return len(self.payload) + sum(a.nbytes for a in self.np_arrays)


def _native_codec():
    from .. import native

    return native.get_codec()


def serialize(obj: Any):
    """Serialize an arbitrary python object, extracting arrays out of band.

    Uses the native C++ codec when available (tag-based fast path, ~10x
    faster for control messages); falls back to pickle with array
    extraction. Both produce self-describing wire bytes via :func:`pack`.
    """
    codec = _native_codec()
    if codec is not None:
        header, arrays = codec.dumps(obj)
        return NativePayload(header, arrays)
    return _py_serialize(obj)


def _py_serialize(obj: Any) -> SerializedPayload:
    arrays: List[ArrayRef] = []
    bio = io.BytesIO()
    _Pickler(bio, arrays).dump(obj)
    return SerializedPayload(bio.getvalue(), arrays)


_native_borrow_ok: Any = None  # None = unprobed; codec borrow support cache


def _probe_borrow(codec) -> bool:
    """Does this codec build accept ``loads(payload, arrays, borrow)``?
    Probed ONCE on a tiny sentinel round-trip — classifying a real
    payload's decode TypeError as "old codec" would silently disable the
    zero-copy path for the life of the process and mask the actual error."""
    global _native_borrow_ok
    if _native_borrow_ok is None:
        try:
            header, arrays = codec.dumps({"p": np.zeros(1, np.float32)})
            codec.loads(header, arrays, True)
            _native_borrow_ok = True
        except Exception:  # noqa: BLE001 - any sentinel failure: copy path
            _native_borrow_ok = False
    return _native_borrow_ok


def deserialize(sp, borrow: bool = False) -> Any:
    """Decode a payload back into python objects.

    ``borrow=True`` skips the defensive copy of numpy array leaves: they come
    back as read-only views straight over the receive buffer (zero payload
    bytes copied).  Only for callers that fully consume the arrays before the
    buffer is recycled — i.e. within the RPC handler call that received the
    frame (the bucketed gradient combine).  The copying default stays for
    user-facing RPC; jax leaves always copy (device_put must own memory).
    """
    global _native_borrow_ok
    if isinstance(sp, NativePayload):
        codec = _native_codec()
        if codec is None:  # built by a peer; we can't decode without it
            raise RuntimeError("native codec payload but codec unavailable")
        if borrow and _probe_borrow(codec):
            return codec.loads(sp.payload, sp.np_arrays, True)
        return codec.loads(sp.payload, sp.np_arrays)
    return _Unpickler(io.BytesIO(sp.payload), sp.arrays, borrow=borrow).load()


# ---------------------------------------------------------------------------
# Wire packing.  Body layout (all little-endian), first byte = codec id:
#
# codec 0 (python pickle path):
#   u8 0 | u32 payload_len | payload bytes | u16 n_arrays
#   per array: u8 kind | u16 dtype_len | dtype utf8 | u8 ndim | u64*ndim shape
#              | u64 data_len | data bytes
# codec 1 (native C++ codec; array metadata lives inside the header):
#   u8 1 | u32 header_len | header bytes | u16 n_arrays
#   per array: u64 data_len | data bytes
#
# The reference's equivalent is the iovec construction in
# ``src/transports/ipc.cc:61-98`` (header + payload + one iovec per tensor).
# Both sides must agree on codec availability (same build on every peer).
# ---------------------------------------------------------------------------

_KINDS = {"np": 0, "jax": 1}
_KINDS_INV = {v: k for k, v in _KINDS.items()}


def pack(sp) -> List[bytes]:
    """Return a list of byte chunks (iovec-style) encoding the payload."""
    if isinstance(sp, NativePayload):
        chunks: List[bytes] = [struct.pack("<BI", 1, len(sp.payload)), sp.payload]
        chunks.append(struct.pack("<H", len(sp.np_arrays)))
        for a in sp.np_arrays:
            chunks.append(struct.pack("<Q", a.nbytes))
            chunks.append(_raw_data(a))
        return chunks
    chunks = [struct.pack("<BI", 0, len(sp.payload)), sp.payload]
    chunks.append(struct.pack("<H", len(sp.arrays)))
    for a in sp.arrays:
        dt = a.dtype.encode()
        hdr = struct.pack("<BH", _KINDS[a.kind], len(dt)) + dt
        hdr += struct.pack("<B", len(a.shape)) + struct.pack(f"<{len(a.shape)}Q", *a.shape)
        hdr += struct.pack("<Q", len(a.data) if not isinstance(a.data, memoryview) else a.data.nbytes)
        chunks.append(hdr)
        chunks.append(a.data)
    return chunks


def pack_bytes(sp: SerializedPayload) -> bytes:
    return b"".join(bytes(c) for c in pack(sp))


def unpack(buf, offset: int = 0):
    """Parse a packed body from ``buf`` (bytes/memoryview) starting at offset."""
    mv = memoryview(buf)
    (codec_id,) = struct.unpack_from("<B", mv, offset)
    offset += 1
    if codec_id == 1:
        (hlen,) = struct.unpack_from("<I", mv, offset)
        offset += 4
        header = bytes(mv[offset : offset + hlen])
        offset += hlen
        (narr,) = struct.unpack_from("<H", mv, offset)
        offset += 2
        buffers = []
        for _ in range(narr):
            (nbytes,) = struct.unpack_from("<Q", mv, offset)
            offset += 8
            buffers.append(mv[offset : offset + nbytes])
            offset += nbytes
        return NativePayload(header, buffers)
    (plen,) = struct.unpack_from("<I", mv, offset)
    offset += 4
    payload = bytes(mv[offset : offset + plen])
    offset += plen
    (narr,) = struct.unpack_from("<H", mv, offset)
    offset += 2
    arrays: List[ArrayRef] = []
    for _ in range(narr):
        kind, dlen = struct.unpack_from("<BH", mv, offset)
        offset += 3
        dtype = bytes(mv[offset : offset + dlen]).decode()
        offset += dlen
        (ndim,) = struct.unpack_from("<B", mv, offset)
        offset += 1
        shape = struct.unpack_from(f"<{ndim}Q", mv, offset) if ndim else ()
        offset += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", mv, offset)
        offset += 8
        data = mv[offset : offset + nbytes]
        offset += nbytes
        arrays.append(ArrayRef(dtype, tuple(shape), _KINDS_INV[kind], data))
    return SerializedPayload(payload, arrays)


def dumps(obj: Any) -> bytes:
    """One-shot: object → single bytes blob (payload + arrays)."""
    return pack_bytes(serialize(obj))


def dumps_portable(obj: Any) -> bytes:
    """One-shot using the always-available pickle codec — for handshakes that
    must parse before codec support is negotiated."""
    return pack_bytes(_py_serialize(obj))


def native_available() -> bool:
    return _native_codec() is not None


def loads(buf, borrow: bool = False) -> Any:
    """One-shot inverse of :func:`dumps`.  ``borrow=True`` returns numpy
    leaves as zero-copy views into ``buf`` (see :func:`deserialize`)."""
    return deserialize(unpack(buf), borrow=borrow)
