"""Flight recorder: a bounded in-memory ring of recent *notable* events.

The span tracer records everything and overflows quickly under load; the
metrics registry keeps totals but no ordering.  The flight recorder sits
between them: subsystems append one-line events at state transitions that
matter for postmortems — epoch bumps, elections, broker failovers, hot
swaps, watchdog expiries, scale events — and the newest few hundred are
dumped verbatim alongside the SIGUSR1 / watchdog diagnostics
(:func:`moolib_tpu.telemetry.exporters.dump_diagnostics`).  A soak kill
then shows *what the process believed was happening* in its last seconds
without any log archaeology.

Events are wall-clock stamped (they must line up with other hosts' logs),
mirror into the span tracer as instant events (so they also appear on the
Chrome timeline), and cost one deque append — safe from IO threads and
signal-adjacent paths.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

from . import tracing

__all__ = [
    "FlightRecorder",
    "get_flight_recorder",
    "flight_event",
    "format_tail",
]


class FlightRecorder:
    """Bounded ring of ``(unix_time, name, args)`` events."""

    def __init__(self, capacity: int = 512):
        self._events: deque = deque(maxlen=capacity)

    def event(self, name: str, **args) -> None:
        """Record one event; also mirrored into the default span tracer as
        an instant event so merged traces show it in place."""
        self._events.append((time.time(), name, args or None))
        try:
            tracing.get_tracer().event(name, **args)
        except Exception:  # noqa: BLE001 — recording must never raise
            pass

    def events(self) -> List[Tuple[float, str, Optional[dict]]]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def format_tail(self, limit: Optional[int] = None) -> str:
        """Human-readable tail for diagnostic dumps (newest last).  Only
        formats already-recorded tuples — safe from a signal handler."""
        events = self.events()
        if limit is not None:
            events = events[-limit:]
        if not events:
            return "--- flight recorder: empty ---\n"
        lines = [f"--- flight recorder (last {len(events)} events) ---\n"]
        for t, name, args in events:
            stamp = time.strftime("%H:%M:%S", time.localtime(t)) + f".{int(t % 1 * 1000):03d}"
            if args:
                kv = " ".join(f"{k}={v}" for k, v in args.items())
                lines.append(f"{stamp} {name} {kv}\n")
            else:
                lines.append(f"{stamp} {name}\n")
        return "".join(lines)


_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = FlightRecorder()
    return _default


def flight_event(name: str, **args) -> None:
    """``telemetry.flight_event("group.epoch", epoch=7)`` against the
    process-default recorder."""
    get_flight_recorder().event(name, **args)


def format_tail(limit: Optional[int] = None) -> str:
    return get_flight_recorder().format_tail(limit)
