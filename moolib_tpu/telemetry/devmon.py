"""Device performance plane: compile, HBM, and MFU accounting.

The cohort observability plane can say *that* a step is slow; this module
says *why*.  Four sub-planes, all publishing through the process metrics
registry (docs/TELEMETRY.md "Device performance plane"):

- **Compile observability** — :func:`install_compile_listeners` subscribes
  to ``jax.monitoring`` (backend compile durations, persistent-cache
  hits/misses) and :func:`instrument_jit` wraps a jitted callable with a
  recompile detector: every *new* abstract input signature increments
  ``jit_compiles_total{fn}``, and a signature change after the first compile
  emits one ``devmon.recompile`` flight event carrying the signature diff
  plus a stderr WARN — the dynamic counterpart of the static
  ``recompile-risk`` lint (docs/ANALYSIS.md).
- **Memory** — :func:`sample_memory` polls ``device.memory_stats()`` into
  ``hbm_bytes_{in_use,peak,limit}{device}`` gauges with high-watermark
  tracking and an OOM-margin warning (``MOOLIB_DEVMON_HBM_WARN_FRACTION``).
  Backends without allocator stats (CPU) fall back to host RSS under
  ``device="host"`` so the gauges populate everywhere.
- **Step cost / MFU** — :func:`step_cost` pulls XLA's counted flops and
  bytes accessed from ``jitted.lower(...).compile().cost_analysis()``
  (cached per abstract signature) and :func:`publish_step` combines it with
  a measured step time into ``step_mfu{fn}`` / ``step_bytes_per_flop{fn}``
  gauges plus a roofline classification (compute- vs memory-bound).  The
  peak FLOP/s and HBM-bandwidth tables live here — the one home for numbers
  ``benchmarks/impala_roofline.py`` and the examples used to hand-maintain.
- **Cohort skew** — lives on
  :meth:`moolib_tpu.telemetry.aggregator.CohortAggregator.step_skew`, which
  fuses per-peer step timings scraped over RPC; this module only documents
  the gauges it publishes.

Everything here is jax-optional at import time: the telemetry package must
stay importable from env workers that never touch jax, so jax imports are
deferred into the functions that need them.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import metrics
from .flightrec import flight_event

__all__ = [
    "StepCost",
    "dispatch_span",
    "install_compile_listeners",
    "install_from_env",
    "instrument_jit",
    "last_recompile",
    "observe_call",
    "set_dispatch_hook",
    "peak_bandwidth",
    "peak_flops",
    "publish_step",
    "reset_for_tests",
    "roofline",
    "sample_memory",
    "start",
    "step_cost",
    "stop",
    "summary_text",
]

_REG = metrics.get_registry()
_M_COMPILES = _REG.counter(
    "jit_compiles_total",
    "distinct abstract input signatures seen per instrumented jit "
    "(each one is an XLA compile)",
    ("fn",),
)
_M_RECOMPILES = _REG.counter(
    "jit_recompiles_total",
    "signature changes after the first compile (each emitted a "
    "devmon.recompile flight event)",
    ("fn",),
)
_M_COMPILE_SECONDS = _REG.histogram(
    "jit_compile_seconds",
    "backend (XLA) compile wall time, from jax.monitoring",
)
_M_CACHE_HITS = _REG.counter(
    "jit_cache_hits_total", "persistent compile-cache hits (jax.monitoring)"
)
_M_CACHE_MISSES = _REG.counter(
    "jit_cache_misses_total", "persistent compile-cache misses (jax.monitoring)"
)
_M_HBM_IN_USE = _REG.gauge(
    "hbm_bytes_in_use", "allocator bytes in use per device (host RSS on CPU)",
    ("device",),
)
_M_HBM_PEAK = _REG.gauge(
    "hbm_bytes_peak", "allocator peak bytes in use per device", ("device",)
)
_M_HBM_LIMIT = _REG.gauge(
    "hbm_bytes_limit", "allocator byte limit per device (host MemTotal on CPU)",
    ("device",),
)
_M_STEP_MFU = _REG.gauge(
    "step_mfu",
    "model FLOPs utilization: XLA-counted flops / step seconds / peak FLOP/s",
    ("fn",),
)
_M_STEP_BPF = _REG.gauge(
    "step_bytes_per_flop",
    "XLA-counted bytes accessed per flop for the step (arithmetic intensity^-1)",
    ("fn",),
)
_M_STEP_FLOPS = _REG.gauge(
    "step_flops", "XLA-counted model flops per step", ("fn",)
)
_M_STEP_BYTES = _REG.gauge(
    "step_bytes_accessed", "XLA-counted bytes accessed per step", ("fn",)
)

# Peak dense (bf16) FLOP/s and HBM bandwidth per chip, from public spec
# sheets.  Substring-matched against ``device.device_kind`` — order matters
# ("v5p" and "v5 lite" before "v5").  These tables are the canonical home;
# impala_roofline.py and the benchmarks consume them from here.
_PEAK_FLOPS: List[Tuple[str, float]] = [
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]
_PEAK_BW: List[Tuple[str, float]] = [
    ("v6e", 1640e9),
    ("v6 lite", 1640e9),
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]
# Unknown device kinds (the CPU backend above all) get a *nominal* peak so
# step_mfu stays finite and tracks relative regressions; the absolute value
# is meaningless there and publish_step says so via ``peak_source``.
NOMINAL_PEAK_FLOPS = 1e12
NOMINAL_PEAK_BW = 100e9

_lock = threading.RLock()
# fn name -> {"seen": set, "last": sig, "compiles": int, "recompiles": int,
#             "last_diff": str|None}
_JIT_STATE: Dict[str, Dict[str, Any]] = {}
_COST_CACHE: Dict[Tuple[str, Any], Optional["StepCost"]] = {}
_WATERMARKS: Dict[str, float] = {}  # device label -> peak bytes_in_use seen
_HBM_WARNED: Dict[str, bool] = {}  # device label -> currently above threshold
_LAST_MEMORY: Dict[str, Dict[str, float]] = {}
_listeners_installed = False
_thread: Optional[threading.Thread] = None
_thread_stop = threading.Event()


# --------------------------------------------------------------------- compile
def install_compile_listeners() -> bool:
    """Subscribe to ``jax.monitoring``: backend compile durations feed
    ``jit_compile_seconds``; persistent compile-cache hit/miss events feed
    ``jit_cache_{hits,misses}_total``.  Idempotent; returns False when the
    listeners were already installed (or jax.monitoring is unavailable)."""
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return False
        try:
            from jax import monitoring  # deferred: telemetry imports without jax
        except Exception:  # noqa: BLE001 — no jax, no compile plane
            return False

        def _on_duration(key: str, dur: float, **kw) -> None:
            if "backend_compile" in key:
                _M_COMPILE_SECONDS.observe(dur)

        def _on_event(key: str, **kw) -> None:
            if key.endswith("cache_hits"):
                _M_CACHE_HITS.inc()
            elif key.endswith("cache_misses"):
                _M_CACHE_MISSES.inc()

        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:  # noqa: BLE001 — observability must not break startup
            return False
        _listeners_installed = True
        return True


def _leaf_sig(x) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{tuple(shape)}/{dtype}"
    return type(x).__name__


def _signature(args, kwargs):
    """Cheap abstract signature of a call: the treedef plus per-leaf
    (shape, dtype) strings — exactly what decides whether jax.jit retraces
    (python-scalar leaves collapse to their type: jit weak-types them, so
    value changes don't recompile and must not count here)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_sig(l) for l in leaves))


def _diff_sigs(old, new) -> str:
    """Compact human diff between two signatures, for the flight event."""
    if old[0] != new[0]:
        return f"tree structure changed: {old[0]} -> {new[0]}"
    parts = []
    o, n = old[1], new[1]
    for i in range(max(len(o), len(n))):
        ov = o[i] if i < len(o) else "<absent>"
        nv = n[i] if i < len(n) else "<absent>"
        if ov != nv:
            parts.append(f"leaf[{i}]: {ov} -> {nv}")
    return "; ".join(parts) or "signatures differ"


# Optional (fn, t0_ns, t1_ns) listener for instrumented dispatches — the
# seam telemetry.timeline uses to anchor capture windows onto train steps.
# Module-global read (no lock) on the call path; None means untimed.
_dispatch_hook = None


def set_dispatch_hook(hook) -> None:
    """Install (or clear, with None) the dispatch listener.  The hook is
    called as ``hook(name, t0_ns, t1_ns)`` with perf_counter_ns bounds of
    each instrumented call; it must be cheap and must not raise."""
    global _dispatch_hook
    _dispatch_hook = hook


class dispatch_span:
    """Context manager equivalent of the `_InstrumentedJit` timing for call
    sites that wrap their own dispatch (parallel/train.py's step closure):
    feeds the dispatch hook when one is installed, otherwise free."""

    __slots__ = ("_name", "_t0")

    def __init__(self, name: str):
        self._name = name
        self._t0 = None

    def __enter__(self):
        if _dispatch_hook is not None:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        hook = _dispatch_hook
        if hook is not None and self._t0 is not None:
            try:
                hook(self._name, self._t0, time.perf_counter_ns())
            except Exception:  # noqa: BLE001 — listener must never break the step
                pass
        return False


class _InstrumentedJit:
    """Callable wrapper around a jitted function that tracks abstract input
    signatures.  Attribute access (``lower``, ``_cache_size``, ...) forwards
    to the wrapped jit so AOT paths and tests see the real object."""

    __slots__ = ("_fn", "_name")

    def __init__(self, fn, name: str):
        self._fn = fn
        self._name = name

    def __call__(self, *args, **kwargs):
        try:
            record_signature(self._name, _signature(args, kwargs))
        except Exception:  # noqa: BLE001 — accounting must never break the step
            pass
        hook = _dispatch_hook
        if hook is None:
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter_ns()
        try:
            return self._fn(*args, **kwargs)
        finally:
            try:
                hook(self._name, t0, time.perf_counter_ns())
            except Exception:  # noqa: BLE001 — listener must never break the step
                pass

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument_jit(fn, name: str):
    """Wrap a jitted callable with the recompile detector (idempotent on
    already-wrapped callables)."""
    if isinstance(fn, _InstrumentedJit):
        return fn
    return _InstrumentedJit(fn, name)


def record_signature(name: str, sig) -> bool:
    """Feed one observed call signature to the detector; returns True when
    the signature is new (== an XLA compile).  A new signature after the
    first emits exactly one ``devmon.recompile`` flight event + WARN;
    returning to an already-seen signature is silent (jit serves it from
    cache — no compile happened)."""
    with _lock:
        st = _JIT_STATE.get(name)
        if st is None:
            st = _JIT_STATE[name] = {
                "seen": set(), "last": None, "compiles": 0,
                "recompiles": 0, "last_diff": None,
            }
        fresh = sig not in st["seen"]
        if fresh:
            st["seen"].add(sig)
            st["compiles"] += 1
            recompile = st["last"] is not None
            if recompile:
                st["recompiles"] += 1
                st["last_diff"] = _diff_sigs(st["last"], sig)
        prev_diff = st["last_diff"]
        st["last"] = sig
    if fresh:
        _M_COMPILES.inc(fn=name)
        if recompile:
            _M_RECOMPILES.inc(fn=name)
            flight_event("devmon.recompile", fn=name, diff=prev_diff)
            sys.stderr.write(
                f"moolib_tpu.devmon: WARN recompile of {name!r}: {prev_diff}\n"
            )
    return fresh


def observe_call(name: str, args=(), kwargs=None) -> None:
    """Record one call's abstract signature for ``name`` without wrapping
    the callable — the seam for step functions that are closures rather
    than raw jits (parallel/train.py).  Never raises."""
    try:
        record_signature(name, _signature(args, kwargs or {}))
    except Exception:  # noqa: BLE001 — accounting must never break the step
        pass


def last_recompile(name: str) -> Optional[str]:
    """The most recent signature diff that triggered a recompile of ``name``
    (None when the fn never recompiled)."""
    with _lock:
        st = _JIT_STATE.get(name)
        return st["last_diff"] if st else None


# ---------------------------------------------------------------------- memory
def _host_memory() -> Optional[Dict[str, float]]:
    """RSS + MemTotal fallback for backends without allocator stats."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        rss = rss_pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None
    limit = 0.0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    limit = float(line.split()[1]) * 1024.0
                    break
    except (OSError, ValueError, IndexError):
        pass
    return {"bytes_in_use": float(rss), "bytes_limit": limit}


def _warn_fraction() -> float:
    try:
        return float(os.environ.get("MOOLIB_DEVMON_HBM_WARN_FRACTION", "0.9"))
    except ValueError:
        return 0.9


def sample_memory() -> Dict[str, Dict[str, float]]:
    """One memory sample across ``jax.local_devices()`` into the
    ``hbm_bytes_*`` gauges, with high-watermark tracking and an OOM-margin
    warning: crossing ``MOOLIB_DEVMON_HBM_WARN_FRACTION`` of the limit emits
    a ``devmon.hbm_pressure`` flight event once per excursion (re-armed when
    usage drops back under).  Devices without ``memory_stats()`` (CPU)
    collapse into one host-RSS sample under ``device="host"``."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no jax backend, fall through to host
        devices = []
    out: Dict[str, Dict[str, float]] = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-device stats are best-effort
            ms = None
        if not ms:
            continue
        label = f"{d.platform}:{d.id}"
        out[label] = {
            "bytes_in_use": float(ms.get("bytes_in_use", 0.0)),
            "bytes_peak": float(
                ms.get("peak_bytes_in_use", ms.get("bytes_in_use", 0.0))
            ),
            "bytes_limit": float(ms.get("bytes_limit", 0.0)),
        }
    if not out:
        host = _host_memory()
        if host is not None:
            out["host"] = {
                "bytes_in_use": host["bytes_in_use"],
                "bytes_peak": host["bytes_in_use"],
                "bytes_limit": host["bytes_limit"],
            }
    frac = _warn_fraction()
    for label, row in out.items():
        with _lock:
            wm = max(_WATERMARKS.get(label, 0.0), row["bytes_in_use"],
                     row.get("bytes_peak", 0.0))
            _WATERMARKS[label] = wm
            _LAST_MEMORY[label] = dict(row)
        row["bytes_peak"] = max(row.get("bytes_peak", 0.0), wm)
        _M_HBM_IN_USE.set(row["bytes_in_use"], device=label)
        _M_HBM_PEAK.set(row["bytes_peak"], device=label)
        _M_HBM_LIMIT.set(row["bytes_limit"], device=label)
        limit = row["bytes_limit"]
        if limit > 0:
            over = row["bytes_in_use"] / limit >= frac
            with _lock:
                warned = _HBM_WARNED.get(label, False)
                _HBM_WARNED[label] = over
            if over and not warned:
                flight_event(
                    "devmon.hbm_pressure",
                    device=label,
                    in_use=int(row["bytes_in_use"]),
                    limit=int(limit),
                    fraction=round(row["bytes_in_use"] / limit, 3),
                )
                sys.stderr.write(
                    f"moolib_tpu.devmon: WARN {label} at "
                    f"{row['bytes_in_use'] / limit:.0%} of its memory limit\n"
                )
    return out


# --------------------------------------------------------------- step cost/MFU
class StepCost:
    """XLA-counted cost of one step: flops + bytes accessed."""

    __slots__ = ("flops", "bytes_accessed")

    def __init__(self, flops: float, bytes_accessed: float):
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)

    @property
    def arithmetic_intensity(self) -> Optional[float]:
        return self.flops / self.bytes_accessed if self.bytes_accessed else None

    def __repr__(self):
        return f"StepCost(flops={self.flops:.3g}, bytes_accessed={self.bytes_accessed:.3g})"


def step_cost(name: str, jitted, *args, **kwargs) -> Optional["StepCost"]:
    """XLA cost analysis of ``jitted(*args, **kwargs)``, cached per abstract
    signature (lowering is pure: donated buffers are NOT consumed).  When
    the step already compiled with these avals the ``.compile()`` here is a
    jit-cache hit, so calling this after the first real step is cheap.
    Returns None when the backend offers no usable analysis."""
    try:
        sig = (name, _signature(args, kwargs))
    except Exception:  # noqa: BLE001 — unflattenable args: no analysis
        return None
    with _lock:
        if sig in _COST_CACHE:
            return _COST_CACHE[sig]
    cost = None
    try:
        lowered = jitted.lower(*args, **kwargs)
        try:
            analysis = lowered.compile().cost_analysis()
        except Exception:  # noqa: BLE001 — fall back to unoptimized analysis
            analysis = lowered.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if analysis:
            flops = float(analysis.get("flops", 0.0))
            byts = float(analysis.get("bytes accessed", 0.0))
            if flops > 0:
                cost = StepCost(flops, byts)
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        cost = None
    with _lock:
        _COST_CACHE[sig] = cost
    if cost is not None:
        _M_STEP_FLOPS.set(cost.flops, fn=name)
        _M_STEP_BYTES.set(cost.bytes_accessed, fn=name)
    return cost


def peak_flops(device_kind: Optional[str] = None) -> Tuple[float, str]:
    """Peak dense FLOP/s for a device kind: ``MOOLIB_DEVMON_PEAK_FLOPS``
    override > spec table > nominal (unknown kinds — CPU).  Returns
    ``(flops_per_s, source)`` with source in {"env", "table", "nominal"}."""
    env = os.environ.get("MOOLIB_DEVMON_PEAK_FLOPS")
    if env:
        try:
            return float(env), "env"
        except ValueError:
            pass
    k = (device_kind or "").lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in k:
            return peak, "table"
    return NOMINAL_PEAK_FLOPS, "nominal"


def peak_bandwidth(device_kind: Optional[str] = None) -> Tuple[float, str]:
    """Peak HBM bytes/s for a device kind (same resolution order as
    :func:`peak_flops`; override knob ``MOOLIB_DEVMON_PEAK_BW``)."""
    env = os.environ.get("MOOLIB_DEVMON_PEAK_BW")
    if env:
        try:
            return float(env), "env"
        except ValueError:
            pass
    k = (device_kind or "").lower()
    for sub, bw in _PEAK_BW:
        if sub in k:
            return bw, "table"
    return NOMINAL_PEAK_BW, "nominal"


def _device_kind() -> Optional[str]:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend: nominal peaks apply
        return None


def roofline(
    flops: float, bytes_accessed: float, device_kind: Optional[str] = None
) -> Dict[str, Any]:
    """Roofline classification for a step: arithmetic intensity vs the
    chip's ridge point (peak_flops / peak_bw).  AI below the ridge means the
    step is HBM-bound; above, compute-bound."""
    pf, pf_src = peak_flops(device_kind)
    pb, pb_src = peak_bandwidth(device_kind)
    out: Dict[str, Any] = {
        "peak_flops": pf,
        "peak_bw": pb,
        "peak_source": pf_src if pf_src == pb_src else f"{pf_src}/{pb_src}",
    }
    if not bytes_accessed or not flops:
        out["bound"] = None
        return out
    ai = flops / bytes_accessed
    ridge = pf / pb
    out["arithmetic_intensity_flop_per_byte"] = ai
    out["ridge_flop_per_byte"] = ridge
    out["min_step_s_compute"] = flops / pf
    out["min_step_s_memory"] = bytes_accessed / pb
    out["roofline_mfu_ceiling"] = min(1.0, ai / ridge)
    out["bound"] = "memory" if ai < ridge else "compute"
    return out


def publish_step(
    name: str,
    cost: Optional["StepCost"],
    step_seconds: float,
    device_kind: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Combine an XLA step cost with a measured step time into the
    ``step_mfu{fn}`` / ``step_bytes_per_flop{fn}`` gauges plus the roofline
    verdict.  Returns ``{"mfu", "bytes_per_flop", "bound", ...}`` (None when
    there is nothing to publish)."""
    if cost is None or step_seconds <= 0 or cost.flops <= 0:
        return None
    if device_kind is None:
        device_kind = _device_kind()
    roof = roofline(cost.flops, cost.bytes_accessed, device_kind)
    mfu = cost.flops / step_seconds / roof["peak_flops"]
    bpf = cost.bytes_accessed / cost.flops
    _M_STEP_MFU.set(mfu, fn=name)
    _M_STEP_BPF.set(bpf, fn=name)
    return {
        "mfu": mfu,
        "bytes_per_flop": bpf,
        "bound": roof.get("bound"),
        "peak_source": roof["peak_source"],
        "roofline": roof,
    }


# ------------------------------------------------------------------- lifecycle
def start(interval: float) -> bool:
    """Background memory-sampling thread (daemon; one per process)."""
    global _thread
    with _lock:
        if _thread is not None and _thread.is_alive():
            return False
        _thread_stop.clear()

        def _loop():
            while not _thread_stop.wait(interval):
                try:
                    sample_memory()
                except Exception:  # noqa: BLE001 — sampling must never crash the run
                    pass

        _thread = threading.Thread(target=_loop, name="devmon-mem", daemon=True)
        _thread.start()
        return True


def stop() -> None:
    global _thread
    with _lock:
        t, _thread = _thread, None
    if t is not None:
        _thread_stop.set()
        t.join(timeout=1.0)


def install_from_env() -> dict:
    """Wire the device plane per the environment: compile listeners when jax
    is already in the process (env workers that never import jax skip them),
    and the periodic memory sampler when ``MOOLIB_DEVMON_INTERVAL`` > 0.
    Called by :func:`moolib_tpu.telemetry.init_from_env`; idempotent."""
    listeners = False
    if "jax" in sys.modules:
        listeners = install_compile_listeners()
    interval = 0.0
    raw = os.environ.get("MOOLIB_DEVMON_INTERVAL")
    if raw:
        try:
            interval = float(raw)
        except ValueError:
            interval = 0.0
    started = start(interval) if interval > 0 else False
    return {"listeners": listeners, "interval": interval if started else None}


def summary_text() -> str:
    """Devmon section for :func:`~moolib_tpu.telemetry.exporters.dump_diagnostics`:
    per-device HBM watermarks, compile counts, and the last recompile
    signature diff per fn.  Formats already-collected dicts only — safe from
    a signal handler."""
    with _lock:
        jits = {k: dict(v) for k, v in _JIT_STATE.items()}
        marks = dict(_WATERMARKS)
        mem = {k: dict(v) for k, v in _LAST_MEMORY.items()}
    lines = ["--- devmon (device performance plane) ---\n"]
    if marks:
        for label in sorted(marks):
            row = mem.get(label, {})
            lines.append(
                f"memory {label}: watermark={marks[label] / 1e6:.1f}MB"
                f" in_use={row.get('bytes_in_use', 0.0) / 1e6:.1f}MB"
                f" limit={row.get('bytes_limit', 0.0) / 1e6:.1f}MB\n"
            )
    else:
        lines.append("memory: no samples yet\n")
    if jits:
        for name in sorted(jits):
            st = jits[name]
            lines.append(
                f"jit {name}: compiles={st['compiles']}"
                f" recompiles={st['recompiles']}\n"
            )
            if st["last_diff"]:
                lines.append(f"  last recompile: {st['last_diff']}\n")
    else:
        lines.append("jit: no instrumented callables yet\n")
    return "".join(lines)


def reset_for_tests() -> None:
    """Drop detector / cost-cache / watermark state (test isolation only;
    registered metrics reset separately via the registry)."""
    global _dispatch_hook
    stop()
    _dispatch_hook = None
    with _lock:
        _JIT_STATE.clear()
        _COST_CACHE.clear()
        _WATERMARKS.clear()
        _HBM_WARNED.clear()
        _LAST_MEMORY.clear()
