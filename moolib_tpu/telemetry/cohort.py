"""Cohort aggregation: ship registry counter deltas on the existing
``GlobalStatsAccumulator`` reduce so leader logs show fleet-wide rates.

:class:`CohortCounters` implements the same snapshot/delta/apply_delta
protocol as ``StatSum``/``StatMean`` (``moolib_tpu/utils/stats.py``), but
over the *whole registry's counter series* at once: its delta is a flat
``{series_name: increment}`` dict.  Drop one into the stats dict an agent
already reduces::

    stats["telemetry"] = telemetry.CohortCounters()
    ...
    global_stats.reduce(stats)          # unchanged call
    stats["telemetry"].value("envpool_steps_total")   # fleet-wide total

No second allreduce, no extra wire protocol: the deltas piggyback on the
agent's periodic stats round (``examples/common`` reduces dict deltas
key-wise).  Remote contributions accumulate in an overlay — local
instruments are never written to, so process-local exporters stay honest."""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .metrics import Registry, get_registry

__all__ = ["CohortCounters"]


class _CounterSnapshot:
    """Frozen counter values; the delta-protocol baseline object."""

    __slots__ = ("values",)

    def __init__(self, values: Dict[str, float]):
        self.values = values

    def apply_delta(self, d: Dict[str, float]) -> None:
        """No-op, deliberately.  ``GlobalStatsAccumulator`` applies remote
        contributions to the delta baseline because ``StatSum.value`` is the
        *merged* total — here ``delta()`` reads the local instruments only
        (remote lives in the stat's overlay), so folding remote into the
        baseline would subtract other peers' progress from the next local
        delta and re-broadcast it as negative."""


class CohortCounters:
    """Registry counters as one cohort-reducible stat (see module doc)."""

    def __init__(self, registry: Optional[Registry] = None, prefix: str = ""):
        self._registry = registry or get_registry()
        self._prefix = prefix
        self._lock = threading.Lock()
        self._remote: Dict[str, float] = {}

    def _local(self) -> Dict[str, float]:
        vals = self._registry.counter_values()
        if self._prefix:
            vals = {k: v for k, v in vals.items() if k.startswith(self._prefix)}
        return vals

    # ---------------------------------------------------- delta protocol
    def snapshot(self) -> _CounterSnapshot:
        return _CounterSnapshot(self._local())

    def delta(self, prev: _CounterSnapshot) -> Dict[str, float]:
        base = prev.values
        cur = self._local()
        # Series can appear over time (a new label set binds); missing in
        # the baseline means it started at zero.
        return {k: v - base.get(k, 0.0) for k, v in cur.items()}

    def apply_delta(self, d: Dict[str, float]) -> None:
        with self._lock:
            for k, v in d.items():
                self._remote[k] = self._remote.get(k, 0.0) + v

    def reset(self) -> None:
        """Counters are monotonic — windowed reset is a no-op (matches
        ``StatSum`` semantics under ``GlobalStatsAccumulator.reset``)."""

    # ---------------------------------------------------------- reading
    def value(self, series: str) -> float:
        """Fleet-wide total for one series: local counter + every remote
        contribution learned through the reduce."""
        with self._lock:
            remote = self._remote.get(series, 0.0)
        return self._local().get(series, 0.0) + remote

    def result(self) -> Dict[str, float]:
        """Fleet-wide totals for every known series."""
        out = self._local()
        with self._lock:
            for k, v in self._remote.items():
                out[k] = out.get(k, 0.0) + v
        return out
