"""Process-wide metrics registry: labeled counters, gauges, histograms.

The reference moolib exposes only ``debug_info`` string dumps (SURVEY §5.1);
this is the uniform replacement: every subsystem registers named instruments
against one process registry and exporters (Prometheus text, JSONL snapshots,
SIGUSR1 dumps — :mod:`moolib_tpu.telemetry.exporters`) read them without the
subsystems knowing.  Stdlib only: importable from env workers, benchmarks,
and the docs generator without touching jax.

Hot-path design: callers bind a labeled child once (``counter.labels(...)``
at wiring time — e.g. per RPC connection) and the per-event cost is one
``child.inc(n)``: a single uncontended ``threading.RLock`` acquire around a
float add (reentrant so the SIGUSR1/watchdog diagnostics dump can't
self-deadlock against an interrupted update).  CPython can't do true
lock-free, but the lock is per-child, never shared across metrics, and held
for two bytecodes — cheap enough for the per-frame RPC path (~100 ns), and
consistent reads come for free.

Naming follows Prometheus conventions: ``snake_case``, ``_total`` suffix on
counters, base-unit ``_seconds``/``_bytes`` suffixes.  Metric names are
documented in docs/TELEMETRY.md; add new ones there.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "DEFAULT_TIME_BUCKETS",
    "DEFAULT_SIZE_BUCKETS",
]

# Latency buckets: 100 us .. ~2 min, roughly x4 per step — wide enough to
# cover an ipc RTT and a wedged collective in the same histogram.
DEFAULT_TIME_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0,
)
# Size buckets (bytes): 256 B .. 1 GiB, x16 per step.
DEFAULT_SIZE_BUCKETS = (
    256.0, 4096.0, 65536.0, 1048576.0, 16777216.0, 268435456.0, 1073741824.0,
)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _max_labelsets() -> int:
    """Per-family cardinality cap.  Unbounded label values (a peer name
    used as a label by a process that churns peers forever) would grow the
    registry — and every exporter payload — without bound; past the cap new
    label sets collapse into one hidden overflow child and
    ``telemetry_dropped_labelsets_total`` counts the drops.  Read per
    overflow decision (not hot: the check only runs when a *new* child
    would be created), so tests and operators can retune it live."""
    try:
        return max(1, int(os.environ.get("MOOLIB_TELEMETRY_MAX_LABELSETS", "1000")))
    except ValueError:
        return 1000


class _Child:
    """One (metric, label-set) time series.

    Locks here (and on histograms / metric families / the registry) are
    REENTRANT: the SIGUSR1/watchdog diagnostics dump formats the registry
    from the main thread, and a signal can land while that same thread is
    inside an ``inc()``/``observe()`` — a plain Lock would self-deadlock
    the process the dump exists to diagnose.  CPython's RLock is C-level
    and keeps the fast path a single uncontended acquire.
    """

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.RLock()
        self._value = 0.0

    def get(self) -> float:
        with self._lock:
            return self._value


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    __slots__ = ()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float]):
        self._lock = threading.RLock()  # reentrant: see _Child
        self._bounds = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # last bucket = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def time(self):
        """Context manager observing the elapsed seconds of its body."""
        return _HistTimer(self)

    def get(self) -> Dict[str, object]:
        with self._lock:
            return {
                "buckets": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }


class _HistTimer:
    __slots__ = ("_h", "_t0")

    def __init__(self, h: _HistogramChild):
        self._h = h

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False


class _Metric:
    """Base: a named family of children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.RLock()  # reentrant: see _Child
        self._children: Dict[Tuple[Tuple[str, str], ...], object] = {}
        self._registry: Optional["Registry"] = None  # set by Registry._register
        self._overflow = None  # shared sink for label sets past the cap

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labels):
        """Bind (and memoize) the child for one label set.  Unknown or
        missing label names are an error — mismatched label sets would
        render as distinct series of the same family and break aggregation.

        Cardinality guard: once a family holds :func:`_max_labelsets`
        distinct label sets, further NEW sets all bind one shared overflow
        child that is never exported, and each such call increments
        ``telemetry_dropped_labelsets_total`` — bounding memory and export
        size while keeping writers crash-free."""
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, got "
                f"{tuple(sorted(labels))}"
            )
        key = _label_key(labels)
        child = self._children.get(key)
        if child is not None:
            return child
        dropped = False
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.labelnames and len(self._children) >= _max_labelsets():
                    if self._overflow is None:
                        self._overflow = self._new_child()
                    child = self._overflow
                    dropped = True
                else:
                    child = self._children.setdefault(key, self._new_child())
        if dropped:
            # Outside the family lock: the drop counter is another family in
            # the same registry; nesting its child lock under ours would
            # order locks across families.
            reg = self._registry
            if reg is not None:
                reg.counter(
                    "telemetry_dropped_labelsets_total",
                    "new label sets dropped by the per-family cardinality "
                    "cap (MOOLIB_TELEMETRY_MAX_LABELSETS)",
                ).inc()
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(f"metric {self.name!r} requires labels {self.labelnames}")
        return self.labels()

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels_dict, value_or_hist_dict)] for every child."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(k), c.get()) for k, c in items]


class Counter(_Metric):
    """Monotonically increasing count (events, bytes, errors)."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels or self.labelnames else self._default()).inc(amount)


class Gauge(_Metric):
    """Point-in-time value (queue depth, membership size, flags)."""

    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float, **labels) -> None:
        (self.labels(**labels) if labels or self.labelnames else self._default()).set(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels or self.labelnames else self._default()).inc(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        (self.labels(**labels) if labels or self.labelnames else self._default()).dec(amount)


class Histogram(_Metric):
    """Fixed-bucket distribution (latencies, sizes).  Buckets are chosen at
    registration and shared by every label set of the family (Prometheus
    requires it for cross-series aggregation)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value: float, **labels) -> None:
        (self.labels(**labels) if labels or self.labelnames else self._default()).observe(value)

    def time(self, **labels):
        return (self.labels(**labels) if labels or self.labelnames else self._default()).time()


class Registry:
    """A named set of metrics.  ``get_registry()`` returns the process-wide
    default; tests build private ones.  Registration is idempotent: asking
    for an existing (name, kind) returns the existing family, so every
    subsystem can declare its metrics at wiring time without coordination."""

    def __init__(self):
        self._lock = threading.RLock()  # reentrant: see _Child
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind} "
                        f"with labels {m.labelnames}"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            m._registry = self
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram, name, help, labelnames, buckets=buckets)

    def collect(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    # ------------------------------------------------------------- flat views
    def counter_values(self) -> Dict[str, float]:
        """Flat ``name{label="v",...}`` -> value map of every COUNTER series.

        Counters only: they are the sum-aggregatable subset, which is what
        the cohort delta reduce (:mod:`moolib_tpu.telemetry.cohort`) ships —
        gauges and histogram internals don't add meaningfully across peers.
        """
        out: Dict[str, float] = {}
        for m in self.collect():
            if m.kind != "counter":
                continue
            for labels, value in m.samples():
                out[_series_name(m.name, labels)] = value
        return out

    def snapshot(self) -> Dict[str, object]:
        """JSON-ready dump of every metric family (exporters use this)."""
        out: Dict[str, object] = {}
        for m in self.collect():
            fam = {"kind": m.kind, "help": m.help, "series": []}
            if m.kind == "histogram":
                fam["buckets"] = list(m.buckets)
            for labels, value in m.samples():
                fam["series"].append({"labels": labels, "value": value})
            out[m.name] = fam
        return out

    def reset_for_tests(self) -> None:
        """Drop every registered metric.  Test isolation only — production
        code must never reset counters (rates are computed from deltas)."""
        with self._lock:
            self._metrics.clear()


def _series_name(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


_default_registry: Optional[Registry] = None
_default_lock = threading.Lock()


def get_registry() -> Registry:
    """The process-wide registry every subsystem wires into."""
    global _default_registry
    if _default_registry is None:
        with _default_lock:
            if _default_registry is None:
                _default_registry = Registry()
    return _default_registry
