"""Cohort metrics aggregator: one fused view of every peer's registry.

Before this module the only cross-process metrics view was the autoscaler
tailing ``telemetry.jsonl`` files — which requires a shared filesystem and
a supervisor that spawned every peer.  The aggregator instead rides the
broker's discovery surface: ``__broker_list`` names the live cohort
(contributing members AND observers — serving replicas, standbys), each of
which answers a ``__telemetry_snapshot`` RPC with the same JSON row shape
the :class:`~moolib_tpu.telemetry.exporters.JsonlSnapshotter` writes.  The
fused result exposes per-peer-labeled Prometheus text / JSONL and feeds the
autoscaler's :class:`~moolib_tpu.autoscaler.PeerSample` pipeline over RPC,
so fleet supervision works across hosts.

Wiring: every peer that should be scrapable calls
:func:`install_rpc_handlers` on its ``Rpc`` (the serving replica and the
example train loops do this by default); the aggregating process connects
an ``Rpc`` to the broker and polls :meth:`CohortAggregator.scrape`.  A peer
dying mid-scrape costs one per-peer timeout and an
``aggregator_scrape_errors_total`` increment — never the scrape.

The ``__telemetry_profile`` handler makes every scrapable peer remotely
profilable: ``{"action": "start"|"stop"|"window"}`` opens/closes an
on-demand ``jax.profiler`` device-trace window
(:mod:`moolib_tpu.telemetry.profiling`) aligned to host span timestamps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from . import exporters, metrics, tracing

__all__ = [
    "install_rpc_handlers",
    "CohortAggregator",
    "fused_prometheus_text",
]

_REG = metrics.get_registry()
_M_SCRAPES = _REG.counter(
    "aggregator_scrapes_total", "cohort scrape rounds completed"
)
_M_SCRAPE_ERRORS = _REG.counter(
    "aggregator_scrape_errors_total",
    "per-peer snapshot pulls that failed or timed out",
    ("peer",),
)
_M_PEERS = _REG.gauge(
    "aggregator_peers", "peers in the last fused snapshot"
)
_M_SKEW = _REG.gauge(
    "cohort_step_skew_ratio",
    "slowest peer's fused per-step seconds / cohort median (step_skew)",
)
_M_PEER_STEP = _REG.gauge(
    "cohort_peer_step_seconds",
    "per-peer fused step seconds (train dispatch + psum share-down) from "
    "the last two scrapes",
    ("peer",),
)
_M_SCRAPE_SECONDS = _REG.histogram(
    "aggregator_scrape_seconds",
    "per-peer snapshot pull wall time within a scrape (timeouts land at "
    "the per-peer cap)",
    ("peer",),
)

_INSTALLED_FLAG = "_moolib_telemetry_handlers"


def install_rpc_handlers(
    rpc,
    registry: Optional[metrics.Registry] = None,
    tracer: Optional[tracing.Tracer] = None,
) -> bool:
    """Define the ``__telemetry_*`` endpoints on ``rpc`` (idempotent):

    - ``__telemetry_snapshot()`` → ``{"time", "pid", "name", "metrics"}`` —
      the JSONL row shape, so :func:`moolib_tpu.autoscaler.sample_from_snapshot`
      consumes it unchanged.
    - ``__telemetry_trace()`` → this peer's Chrome trace dict (feed files to
      ``scripts/trace_merge.py``).
    - ``__telemetry_profile(action, logdir=None, seconds=None)`` → on-demand
      device profiling (:func:`moolib_tpu.telemetry.profiling.handle_command`).

    Returns False when the endpoints were already installed on this ``rpc``.
    """
    if getattr(rpc, _INSTALLED_FLAG, False):
        return False
    reg = registry or metrics.get_registry()
    tr = tracer or tracing.get_tracer()

    def _snapshot():
        from .flightrec import get_flight_recorder

        return {
            "time": time.time(),
            "pid": os.getpid(),
            "name": rpc.get_name(),
            "metrics": reg.snapshot(),
            # Last flight-recorder entries, newest last — the cohort console
            # (scripts/mtop.py) shows this tail per peer.
            "flight": [
                {"time": t, "name": n, "args": a}
                for t, n, a in get_flight_recorder().events()[-16:]
            ],
        }

    def _trace():
        return tr.chrome_trace()

    def _profile(action: str, logdir: Optional[str] = None, seconds: Optional[float] = None):
        from . import profiling

        return profiling.handle_command(action, logdir=logdir, seconds=seconds)

    rpc.define("__telemetry_snapshot", _snapshot)
    rpc.define("__telemetry_trace", _trace)
    rpc.define("__telemetry_profile", _profile)
    setattr(rpc, _INSTALLED_FLAG, True)
    return True


class CohortAggregator:
    """Pull every broker-discovered peer's registry snapshot over RPC and
    fuse them into one per-peer-labeled view.

    ``rpc`` must be connected (or connectable by gossip) to at least one of
    ``brokers`` — the same client contract as ``ServeClient``.  Peers are
    reached by their broker-advertised names through ``__moolib_find_peer``
    gossip; no address bookkeeping here.
    """

    def __init__(
        self,
        rpc,
        brokers: Union[str, Sequence[str]],
        group: str = "default",
        scrape_timeout: float = 2.0,
        include_observers: bool = True,
        include_self: bool = False,
        peer_timeout: Optional[float] = None,
    ):
        self._rpc = rpc
        self._brokers = [brokers] if isinstance(brokers, str) else list(brokers)
        if not self._brokers:
            raise ValueError("need at least one broker peer name")
        self._group = group
        self._timeout = float(scrape_timeout)
        # Per-peer cap within a scrape, so one wedged peer can't consume the
        # whole shared deadline and stall every later peer's collection (the
        # mtop refresh tick).  Resolution: constructor arg >
        # MOOLIB_AGGREGATOR_SCRAPE_TIMEOUT env > the shared scrape timeout.
        if peer_timeout is None:
            env = os.environ.get("MOOLIB_AGGREGATOR_SCRAPE_TIMEOUT")
            if env:
                try:
                    peer_timeout = float(env)
                except ValueError:
                    peer_timeout = None
        self._peer_timeout = (
            float(peer_timeout)
            if peer_timeout and peer_timeout > 0
            else self._timeout
        )
        self._include_observers = include_observers
        self._include_self = include_self
        self._lock = threading.Lock()
        self._roster: Dict[str, str] = {}  # name -> role
        self._fused: Dict[str, Any] = {"time": 0.0, "peers": {}, "errors": {}}
        self._last_steps: Dict[str, tuple] = {}  # peer -> (time, steps)
        # step_skew() state: peer -> (dispatch_sum, dispatch_count,
        # psum_sum, psum_count) from the previous call, so per-peer step
        # time reflects the window BETWEEN skew computations, not lifetime.
        self._skew_state: Dict[str, tuple] = {}
        self._straggler_streak: tuple = (None, 0)  # (peer, consecutive flags)
        self._straggler_announced: Optional[str] = None

    # ------------------------------------------------------------ discovery
    def discover(self) -> Dict[str, str]:
        """Refresh the roster from the first broker that answers
        ``__broker_list``; on total silence the last roster is kept (a
        scrape through a broker failover degrades, it doesn't blank)."""
        for broker in self._brokers:
            try:
                listing = self._rpc.async_(
                    broker, "__broker_list", self._group
                ).result(self._timeout)
            except Exception:  # noqa: BLE001 — next broker owns this
                continue
            if not isinstance(listing, dict):
                continue
            roster: Dict[str, str] = {}
            for m in listing.get("members") or ():
                roster[m] = "member"
            if self._include_observers:
                for name, role in (listing.get("observers") or {}).items():
                    roster.setdefault(name, role or "observer")
            if not self._include_self:
                roster.pop(self._rpc.get_name(), None)
            with self._lock:
                self._roster = roster
            return dict(roster)
        with self._lock:
            return dict(self._roster)

    # -------------------------------------------------------------- scraping
    def scrape(self) -> Dict[str, Any]:
        """One fused pull: discover, fan out ``__telemetry_snapshot`` to
        every peer concurrently, collect under a shared deadline.  Returns
        (and caches) ``{"time", "peers": {name: row}, "errors": {name:
        reason}}``; a peer that died mid-scrape lands in ``errors`` and
        costs at most the scrape timeout in wall clock."""
        roster = self.discover()
        futures = {
            name: self._rpc.async_(name, "__telemetry_snapshot") for name in roster
        }
        deadline = time.monotonic() + self._timeout
        peers: Dict[str, Any] = {}
        errors: Dict[str, str] = {}
        for name, fut in futures.items():
            t0 = time.monotonic()
            try:
                row = fut.result(
                    max(0.05, min(self._peer_timeout, deadline - time.monotonic()))
                )
            except Exception as e:  # noqa: BLE001 — per-peer failure isolated
                fut.cancel()
                _M_SCRAPE_SECONDS.observe(time.monotonic() - t0, peer=name)
                errors[name] = str(e) or type(e).__name__
                _M_SCRAPE_ERRORS.inc(peer=name)
                continue
            _M_SCRAPE_SECONDS.observe(time.monotonic() - t0, peer=name)
            if isinstance(row, dict) and "metrics" in row:
                row.setdefault("name", name)
                row["role"] = roster.get(name, "member")
                peers[name] = row
            else:
                errors[name] = "malformed snapshot"
                _M_SCRAPE_ERRORS.inc(peer=name)
        fused = {"time": time.time(), "peers": peers, "errors": errors}
        with self._lock:
            self._fused = fused
        _M_SCRAPES.inc()
        _M_PEERS.set(len(peers))
        return fused

    def snapshot(self) -> Dict[str, Any]:
        """The last fused scrape (without pulling again)."""
        with self._lock:
            return self._fused

    # ------------------------------------------------------------ exposition
    def prometheus_text(self) -> str:
        """The last fused scrape as Prometheus text with a ``peer`` label
        on every series."""
        with self._lock:
            peers = self._fused["peers"]
        return fused_prometheus_text(peers)

    def write_jsonl(self, path: str) -> None:
        """Append the last fused scrape as one JSON line (the cohort-level
        analogue of the per-process ``telemetry.jsonl``)."""
        with self._lock:
            fused = self._fused
        with open(path, "a") as f:
            f.write(json.dumps(fused) + "\n")

    # ------------------------------------------------------------ autoscaler
    def peer_samples(self) -> List[Any]:
        """The last fused scrape as :class:`moolib_tpu.autoscaler.PeerSample`
        rows, with step rates from successive scrape deltas — the RPC-pull
        counterpart of ``SubprocessFleet.samples()``."""
        from .. import autoscaler  # deferred: autoscaler imports telemetry

        with self._lock:
            peers = dict(self._fused["peers"])
        out = []
        for name, row in peers.items():
            s = autoscaler.sample_from_snapshot(name, row)
            if s.steps is not None:
                prev = self._last_steps.get(name)
                # A counter BELOW the previous reading means the peer
                # restarted (registry counters never decrease): treat it as
                # fresh rather than publishing a negative rate the policy
                # would read as a stall.
                if prev is not None and s.time > prev[0] and s.steps >= prev[1]:
                    s.step_rate = (s.steps - prev[1]) / (s.time - prev[0])
                self._last_steps[name] = (s.time, s.steps)
            out.append(s)
        # Peers that left the cohort must not pin their last reading forever
        # (a name reused by a respawned peer would inherit a stale delta).
        for gone in set(self._last_steps) - set(peers):
            del self._last_steps[gone]
        return out

    # ----------------------------------------------------------- cohort skew
    @staticmethod
    def _hist_totals(metrics_snap: Dict[str, Any], name: str) -> tuple:
        """(sum, count) across every series of one histogram family in a
        peer's snapshot — the cumulative figures the skew deltas work on."""
        fam = metrics_snap.get(name) or {}
        total, count = 0.0, 0.0
        for s in fam.get("series", ()):
            v = s.get("value")
            if isinstance(v, dict):
                total += float(v.get("sum", 0.0))
                count += float(v.get("count", 0.0))
        return total, count

    def step_skew(self, threshold: float = 1.5, sustain: int = 3) -> Dict[str, Any]:
        """Per-peer straggler attribution from the last fused scrape
        (devmon's cohort sub-plane, docs/TELEMETRY.md "Device performance
        plane").

        Fuses each peer's ``train_step_dispatch_seconds`` and
        ``accum_psum_seconds`` histograms into one per-step wall figure —
        computed over the window since the previous ``step_skew`` call
        (cumulative sum/count deltas), so a recovered peer stops looking
        slow one window later.  Publishes ``cohort_step_skew_ratio``
        (slowest / cohort median) and ``cohort_peer_step_seconds{peer}``;
        when the SAME peer stays above ``threshold`` for ``sustain``
        consecutive calls, one ``devmon.straggler`` flight event names it
        (re-armed when the peer recovers or the straggler moves).

        Returns ``{"ratio", "peers": {name: {...}}, "straggler",
        "sustained"}``; ratio 1.0 with no straggler when fewer than two
        peers report step timings.
        """
        with self._lock:
            peers = dict(self._fused["peers"])
        cur: Dict[str, tuple] = {}
        per_peer: Dict[str, Dict[str, float]] = {}
        for name, row in peers.items():
            met = row.get("metrics") or {}
            d_sum, d_cnt = self._hist_totals(met, "train_step_dispatch_seconds")
            p_sum, p_cnt = self._hist_totals(met, "accum_psum_seconds")
            cur[name] = (d_sum, d_cnt, p_sum, p_cnt)
            prev = self._skew_state.get(name)
            # Window deltas when we have a previous reading and the counters
            # moved forward (a restart resets them — fall back to lifetime).
            if prev is not None and d_cnt > prev[1] and d_sum >= prev[0]:
                dd_sum, dd_cnt = d_sum - prev[0], d_cnt - prev[1]
                dp_sum = max(0.0, p_sum - prev[2])
                dp_cnt = max(0.0, p_cnt - prev[3])
            else:
                dd_sum, dd_cnt, dp_sum, dp_cnt = d_sum, d_cnt, p_sum, p_cnt
            if dd_cnt <= 0:
                continue  # no step timing from this peer (e.g. pure server)
            dispatch = dd_sum / dd_cnt
            psum = dp_sum / dp_cnt if dp_cnt > 0 else 0.0
            per_peer[name] = {
                "step_seconds": dispatch + psum,
                "dispatch_seconds": dispatch,
                "psum_seconds": psum,
            }
        self._skew_state = cur  # prune dead peers with the same assignment
        for name, row in per_peer.items():
            _M_PEER_STEP.set(row["step_seconds"], peer=name)
        if len(per_peer) < 2:
            _M_SKEW.set(1.0)
            self._straggler_streak = (None, 0)
            self._straggler_announced = None
            return {"ratio": 1.0, "peers": per_peer, "straggler": None,
                    "sustained": False}
        times = sorted(r["step_seconds"] for r in per_peer.values())
        median = times[len(times) // 2]
        slowest = max(per_peer, key=lambda n: per_peer[n]["step_seconds"])
        ratio = (per_peer[slowest]["step_seconds"] / median) if median > 0 else 1.0
        _M_SKEW.set(ratio)
        candidate = slowest if ratio >= threshold else None
        last_peer, streak = self._straggler_streak
        streak = streak + 1 if (candidate and candidate == last_peer) else (
            1 if candidate else 0
        )
        self._straggler_streak = (candidate, streak)
        if candidate != self._straggler_announced:
            self._straggler_announced = None
        sustained = bool(candidate) and streak >= sustain
        if sustained and self._straggler_announced != candidate:
            self._straggler_announced = candidate
            from .flightrec import flight_event

            flight_event(
                "devmon.straggler",
                peer=candidate,
                ratio=round(ratio, 2),
                step_seconds=round(per_peer[candidate]["step_seconds"], 4),
                median_seconds=round(median, 4),
            )
        return {"ratio": ratio, "peers": per_peer, "straggler": candidate,
                "sustained": sustained}


def fused_prometheus_text(peers: Dict[str, Dict[str, Any]]) -> str:
    """Merge per-peer registry snapshots (``{peer: {"metrics": ...}}`` rows)
    into one Prometheus exposition with a ``peer`` label on every series."""
    # family name -> {"kind", "help", "buckets"?, "series": [(labels, value)]}
    fams: Dict[str, Dict[str, Any]] = {}
    for peer in sorted(peers):
        met = peers[peer].get("metrics") or {}
        for name in sorted(met):
            fam = met[name]
            dst = fams.setdefault(
                name,
                {
                    "kind": fam.get("kind", "gauge"),
                    "help": fam.get("help", ""),
                    "buckets": fam.get("buckets"),
                    "series": [],
                },
            )
            for s in fam.get("series", ()):
                labels = dict(s.get("labels") or {})
                labels["peer"] = peer
                dst["series"].append((labels, s.get("value")))
    lines: List[str] = []
    fmt_labels = exporters._fmt_labels
    fmt_value = exporters._fmt_value
    for name in sorted(fams):
        fam = fams[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        if fam["kind"] == "histogram":
            bounds = fam.get("buckets") or ()
            for labels, h in fam["series"]:
                if not isinstance(h, dict):
                    continue
                cum = 0
                for bound, n in zip(bounds, h.get("buckets", ())):
                    cum += n
                    lines.append(
                        f"{name}_bucket{fmt_labels(labels, ('le', fmt_value(bound)))} {cum}"
                    )
                hb = h.get("buckets", ())
                cum += hb[-1] if len(hb) > len(bounds) else 0
                lines.append(f"{name}_bucket{fmt_labels(labels, ('le', '+Inf'))} {cum}")
                lines.append(f"{name}_sum{fmt_labels(labels)} {fmt_value(h.get('sum', 0.0))}")
                lines.append(f"{name}_count{fmt_labels(labels)} {h.get('count', 0)}")
        else:
            for labels, v in fam["series"]:
                if v is None:
                    continue
                lines.append(f"{name}{fmt_labels(labels)} {fmt_value(v)}")
    return "\n".join(lines) + "\n"
