"""Fused host+device step timeline: overlap/exposure attribution.

ROADMAP items 2/3 (MPMD pipeline, latency-hiding gradient overlap) are
scheduling changes whose whole payoff is "comm hidden behind compute" —
a quantity neither artifact shows alone: the host span tracer
(:mod:`moolib_tpu.telemetry.tracing`) sees dispatch and RPC wall time but
not what the chip ran, and a ``jax.profiler`` capture
(:mod:`moolib_tpu.telemetry.profiling`) sees device slices but not which
train step they belong to.  This module fuses the two for one short
capture window at a time:

1. a window opens through :mod:`profiling` (so it can never overlap a
   user-requested profile — the profiler is a single slot) and records the
   start anchors ``(unix_time_ns, perf_counter_ns)``;
2. while it is open, every instrumented dispatch
   (:func:`moolib_tpu.telemetry.devmon.instrument_jit` /
   ``parallel.train``'s step wrapper) reports its ``(fn, t0, t1)`` through
   the devmon dispatch hook, and host-side collective / host-blocked
   phases report through :func:`comm_span` / :func:`host_span`
   (accumulator share-down, rollout fetch);
3. on close, the XLA trace-event JSON under the window's logdir is loaded,
   its clock rebased onto the host anchors, and every device slice is
   classified into {compute, collective-comm, host-blocked} by name;
4. wall time between consecutive dispatch starts is one *step* owned by
   the dispatching fn, and each step partitions exactly into

   - **compute** — device compute slices (plus the dispatch interval
     itself, which on CPU *is* the execution),
   - **comm** — collective intervals NOT covered by concurrent compute
     (the *exposed* communication the overlap work must drive to zero;
     collective time under compute is *overlapped* and counted inside
     compute's share),
   - **host** — host-blocked intervals (infeed/outfeed/transfers, and
     host spans fed via :func:`host_span`) not covered by either,
   - **idle** — the remainder,

   so ``step_time_fraction{bucket,fn}`` sums to 1.0 per fn by
   construction.

Exported metrics (docs/TELEMETRY.md "Timeline & overlap"):
``step_time_fraction{bucket,fn}``, ``exposed_comm_seconds`` /
``overlapped_comm_seconds``, ``pipeline_bubble_fraction{stage}`` (per
device track), ``timeline_comm_vs_psum_ratio`` (device+host-measured
collective seconds vs the ``accum_psum_seconds`` growth over the same
window — the cross-check that the two planes agree), plus
``timeline_windows_total`` / ``timeline_ingest_errors_total``.

Periodic windows are off by default: ``MOOLIB_TIMELINE_INTERVAL=N`` opens
one ``MOOLIB_TIMELINE_WINDOW_S``-long window every N instrumented
dispatches (wired by :func:`moolib_tpu.telemetry.init_from_env`).
Everything degrades: no jax, an unparsable capture, or a user profile
holding the slot cost one skipped/host-only window, never the step.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import metrics, profiling, tracing
from .flightrec import flight_event

__all__ = [
    "BUCKETS",
    "classify_name",
    "comm_span",
    "configure",
    "host_span",
    "ingest_window",
    "install_from_env",
    "load_profiler_trace",
    "on_dispatch",
    "reset_for_tests",
    "status",
]

_REG = metrics.get_registry()
_M_FRACTION = _REG.gauge(
    "step_time_fraction",
    "per-fn share of step wall time by bucket (compute / comm = exposed "
    "collectives / host = host-blocked / idle); sums to 1.0 per fn over "
    "the last timeline window",
    ("bucket", "fn"),
)
_M_EXPOSED = _REG.counter(
    "exposed_comm_seconds",
    "collective-comm seconds NOT covered by concurrent compute in timeline "
    "windows (the overlap work's target)",
)
_M_OVERLAPPED = _REG.counter(
    "overlapped_comm_seconds",
    "collective-comm seconds hidden behind concurrent compute in timeline "
    "windows",
)
_M_BUBBLE = _REG.gauge(
    "pipeline_bubble_fraction",
    "idle fraction of each device timeline track over the last window "
    "(per-stage bubble for the MPMD pipeline plane)",
    ("stage",),
)
_M_PSUM_RATIO = _REG.gauge(
    "timeline_comm_vs_psum_ratio",
    "timeline-measured collective seconds / accum_psum_seconds growth over "
    "the same window (cross-check between the device and host planes)",
)
_M_WINDOWS = _REG.counter(
    "timeline_windows_total", "timeline capture windows ingested"
)
_M_ERRORS = _REG.counter(
    "timeline_ingest_errors_total",
    "timeline windows whose device capture failed to load or parse",
)

BUCKETS = ("compute", "comm", "host", "idle")

DEFAULT_WINDOW_S = 0.25

# Substring classification of device slice names.  Collectives first: an
# XLA thunk named "all-reduce-start.1" must not fall through to compute.
_COMM_PATTERNS = (
    "all-reduce", "allreduce", "all-gather", "allgather", "reduce-scatter",
    "reducescatter", "all-to-all", "alltoall", "collective-permute",
    "collectivepermute", "collective", "psum", "ncclallreduce", "send",
    "recv",
)
_HOST_PATTERNS = (
    "infeed", "outfeed", "transfer", "copy", "memcpy", "h2d", "d2h",
    "host_callback", "device_to_host", "host_to_device",
)


def classify_name(name: str) -> str:
    """Bucket for one device-timeline slice name: "comm" for collectives,
    "host" for host<->device transfer/infeed work, else "compute"."""
    n = (name or "").lower()
    for pat in _COMM_PATTERNS:
        if pat in n:
            return "comm"
    for pat in _HOST_PATTERNS:
        if pat in n:
            return "host"
    return "compute"


# ------------------------------------------------------------ interval math
# Intervals are (start, end) float pairs on one axis (seconds here); all
# helpers return sorted, disjoint lists, so measures add exactly and the
# four buckets partition each step by construction.
def _union(iv: Iterable[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, e in sorted((s, e) for s, e in iv if e > s):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _measure(iv: Sequence[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in iv)


def _clip(
    iv: Sequence[Tuple[float, float]], lo: float, hi: float
) -> List[Tuple[float, float]]:
    return [(max(s, lo), min(e, hi)) for s, e in iv if min(e, hi) > max(s, lo)]


def _subtract(
    a: Sequence[Tuple[float, float]], b: Sequence[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """a minus b; both must be sorted+disjoint (outputs of _union/_clip)."""
    out: List[Tuple[float, float]] = []
    bi = 0
    for s, e in a:
        cur = s
        while bi < len(b) and b[bi][1] <= cur:
            bi += 1
        j = bi
        while j < len(b) and b[j][0] < e:
            bs, be = b[j]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


# ----------------------------------------------------------- device capture
def _find_trace_file(logdir: str) -> Optional[str]:
    """Newest ``*.trace.json(.gz)`` under ``logdir`` (the TensorBoard
    layout nests it under plugins/profile/<run>/)."""
    best: Tuple[float, Optional[str]] = (-1.0, None)
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if f.endswith(".trace.json.gz") or f.endswith(".trace.json"):
                p = os.path.join(root, f)
                try:
                    mt = os.path.getmtime(p)
                except OSError:
                    continue
                if mt > best[0]:
                    best = (mt, p)
    return best[1]


def load_profiler_trace(logdir: Optional[str]) -> List[Dict[str, Any]]:
    """Device slices from the newest trace-event JSON under ``logdir``:
    ``[{"name", "ts_us", "dur_us", "track", "bucket"}, ...]`` ("X" events
    only; metadata resolves pid/tid to a readable track label).  Returns
    ``[]`` when there is nothing to load; raises only on a present but
    unparsable file (the caller counts it as an ingest error)."""
    if not logdir:
        return []
    path = _find_trace_file(logdir)
    if path is None:
        return []
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
            data = json.load(f)
    else:
        with open(path, encoding="utf-8", errors="replace") as f:
            data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    pnames: Dict[Any, str] = {}
    tnames: Dict[Tuple[Any, Any], str] = {}
    for ev in events:
        if ev.get("ph") == "M":
            argname = (ev.get("args") or {}).get("name")
            if ev.get("name") == "process_name" and argname:
                pnames[ev.get("pid")] = str(argname)
            elif ev.get("name") == "thread_name" and argname:
                tnames[(ev.get("pid"), ev.get("tid"))] = str(argname)
    out: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        try:
            ts = float(ev["ts"])
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        pid, tid = ev.get("pid"), ev.get("tid")
        track = tnames.get((pid, tid)) or pnames.get(pid) or f"{pid}/{tid}"
        name = str(ev.get("name", ""))
        # The profiler's python tracer emits host call-stack frames named
        # "$file.py:123 fn" — those are not device work, and frame/file
        # names ("send_frame", "collectives.py") shred the substring
        # classifier.  Host time is already accounted by the dispatch and
        # comm/host spans; keep only runtime/device slices.
        if name.startswith("$") or track == "python":
            continue
        out.append(
            {
                "name": name,
                "ts_us": ts,
                "dur_us": dur,
                "track": track,
                "bucket": classify_name(name),
            }
        )
    return out


# -------------------------------------------------------------- attribution
def _host_to_unix_s(t_ns: int, anchor: Tuple[int, int]) -> float:
    """perf_counter_ns -> unix seconds via the window's start anchors."""
    unix_ns, perf_ns = anchor
    return (unix_ns + (t_ns - perf_ns)) / 1e9


def ingest_window(
    steps: Sequence[Tuple[str, int, int]],
    comm_spans: Sequence[Tuple[str, int, int]] = (),
    host_spans: Sequence[Tuple[str, int, int]] = (),
    slices: Sequence[Dict[str, Any]] = (),
    anchor: Optional[Tuple[int, int]] = None,
    window_end_ns: Optional[int] = None,
    psum_host_seconds: Optional[float] = None,
    publish: bool = True,
) -> Dict[str, Any]:
    """Attribute one capture window and (optionally) publish the gauges.

    ``steps`` / ``comm_spans`` / ``host_spans`` are host-clock
    ``(name, t0_ns, t1_ns)`` perf_counter records; ``slices`` come from
    :func:`load_profiler_trace`; ``anchor`` is the window's
    ``(unix_time_ns, perf_counter_ns)`` start pair (defaults to "now",
    which only matters when device slices need rebasing).  Returns the
    report dict tests and the smoke harness consume.
    """
    if anchor is None:
        anchor = (time.time_ns(), time.perf_counter_ns())
    steps = sorted(steps, key=lambda s: s[1])
    report: Dict[str, Any] = {
        "steps": len(steps),
        "slices": len(slices),
        "fns": {},
        "exposed_comm_seconds": 0.0,
        "overlapped_comm_seconds": 0.0,
        "bubble": {},
        "comm_vs_psum_ratio": None,
    }
    if not steps:
        return report

    # Host records onto the unix axis.
    step_pts = [
        (name, _host_to_unix_s(t0, anchor), _host_to_unix_s(t1, anchor))
        for name, t0, t1 in steps
    ]
    w0 = step_pts[0][1]
    w1 = max(s[2] for s in step_pts)
    if window_end_ns is not None:
        w1 = max(w1, _host_to_unix_s(window_end_ns, anchor))
    for _n, t0, t1 in (
        (n, _host_to_unix_s(a, anchor), _host_to_unix_s(b, anchor))
        for n, a, b in list(comm_spans) + list(host_spans)
    ):
        w1 = max(w1, t1)

    # Device slices onto the same axis.  XLA traces usually stamp unix
    # microseconds already; a capture on a private origin (or a synthetic
    # fixture) is rebased so its first slice lands at the window start.
    dev: List[Tuple[str, str, float, float]] = []  # (bucket, track, s, e)
    if slices:
        dmin = min(s["ts_us"] for s in slices)
        span = max(w1 - w0, 1e-6)
        off_s = 0.0
        if abs(dmin / 1e6 - w0) > 10.0 * span:
            off_s = w0 - dmin / 1e6
        for s in slices:
            t0 = s["ts_us"] / 1e6 + off_s
            t1 = t0 + s["dur_us"] / 1e6
            dev.append((s["bucket"], s["track"], t0, t1))

    compute_u = _union(
        [(t0, t1) for _n, t0, t1 in step_pts]
        + [(t0, t1) for b, _tr, t0, t1 in dev if b == "compute"]
    )
    comm_u = _union(
        [
            (_host_to_unix_s(a, anchor), _host_to_unix_s(b, anchor))
            for _n, a, b in comm_spans
        ]
        + [(t0, t1) for b, _tr, t0, t1 in dev if b == "comm"]
    )
    host_u = _union(
        [
            (_host_to_unix_s(a, anchor), _host_to_unix_s(b, anchor))
            for _n, a, b in host_spans
        ]
        + [(t0, t1) for b, _tr, t0, t1 in dev if b == "host"]
    )

    # One step = [this dispatch start, next dispatch start); the last step
    # runs to the window end so trailing comm/idle is attributed, not lost.
    fns: Dict[str, Dict[str, float]] = {}
    total_exposed = 0.0
    total_overlapped = 0.0
    for i, (name, t0, _t1) in enumerate(step_pts):
        end = step_pts[i + 1][1] if i + 1 < len(step_pts) else w1
        if end <= t0:
            continue
        comp = _clip(compute_u, t0, end)
        c = _measure(comp)
        comm_in = _clip(comm_u, t0, end)
        exposed_iv = _subtract(comm_in, comp)
        e = _measure(exposed_iv)
        overlapped = _measure(comm_in) - e
        host_in = _subtract(_subtract(_clip(host_u, t0, end), comp), comm_in)
        h = _measure(host_in)
        dur = end - t0
        row = fns.setdefault(
            name,
            {"compute": 0.0, "comm": 0.0, "host": 0.0, "idle": 0.0,
             "total": 0.0, "steps": 0.0, "overlapped": 0.0},
        )
        row["compute"] += c
        row["comm"] += e
        row["host"] += h
        row["idle"] += max(0.0, dur - c - e - h)
        row["total"] += dur
        row["steps"] += 1
        row["overlapped"] += overlapped
        total_exposed += e
        total_overlapped += overlapped

    for name, row in fns.items():
        total = row["total"] or 1.0
        fracs = {b: row[b] / total for b in BUCKETS}
        report["fns"][name] = {
            "fractions": fracs,
            "seconds": {b: row[b] for b in BUCKETS},
            "overlapped_comm_seconds": row["overlapped"],
            "steps": int(row["steps"]),
            "total_seconds": row["total"],
        }
        if publish:
            for b, v in fracs.items():
                _M_FRACTION.set(v, bucket=b, fn=name)
    report["exposed_comm_seconds"] = total_exposed
    report["overlapped_comm_seconds"] = total_overlapped

    # Per-stage bubble: each device track's idle share of the window.
    tracks: Dict[str, List[Tuple[float, float]]] = {}
    for _b, tr, t0, t1 in dev:
        tracks.setdefault(tr, []).append((t0, t1))
    for tr, iv in tracks.items():
        busy = _measure(_clip(_union(iv), w0, w1))
        frac = max(0.0, 1.0 - busy / max(w1 - w0, 1e-9))
        report["bubble"][tr] = frac
        if publish:
            _M_BUBBLE.set(frac, stage=tr)

    comm_total = total_exposed + total_overlapped
    if psum_host_seconds is not None and psum_host_seconds > 1e-9:
        ratio = comm_total / psum_host_seconds
        report["comm_vs_psum_ratio"] = ratio
        if publish:
            _M_PSUM_RATIO.set(ratio)
    if publish:
        _M_EXPOSED.inc(total_exposed)
        _M_OVERLAPPED.inc(total_overlapped)
        _M_WINDOWS.inc()
        tracing.get_tracer().event(
            "timeline.window",
            steps=len(steps),
            slices=len(slices),
            exposed_comm_s=round(total_exposed, 6),
        )
        flight_event(
            "timeline.window",
            steps=len(steps),
            exposed_comm_s=round(total_exposed, 6),
            overlapped_comm_s=round(total_overlapped, 6),
        )
    return report


# ---------------------------------------------------- periodic window plumbing
_lock = threading.Lock()
_state: Dict[str, Any] = {
    "interval": 0,          # dispatches between windows; 0 = off
    "window_s": DEFAULT_WINDOW_S,
    "device": True,         # open a jax.profiler capture per window
    "calls": 0,
    "opening": False,
    "window": None,         # active window dict
    "window_seq": 0,
    "windows": 0,           # ingested windows (for status())
    "last_report": None,
    "hooked": False,
}


def _psum_total() -> float:
    fam = _REG.snapshot().get("accum_psum_seconds") or {}
    total = 0.0
    for s in fam.get("series", ()):  # type: ignore[union-attr]
        v = s.get("value")
        if isinstance(v, dict):
            total += float(v.get("sum", 0.0))
    return total


def _timeline_logdir(seq: int) -> str:
    base = os.environ.get("MOOLIB_PROFILE_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "moolib_profiles"
    )
    return os.path.join(base, f"timeline-pid{os.getpid()}-{seq}")


def _open_window(seq: int) -> Optional[Dict[str, Any]]:
    """Open one capture window; None when the profiler slot is busy (a
    user-requested profile always wins)."""
    anchor: Optional[Tuple[int, int]] = None
    logdir: Optional[str] = None
    if _state["device"]:
        if profiling.profile_status().get("active"):
            return None
        res = profiling.start_device_trace(_timeline_logdir(seq))
        if res.get("ok"):
            logdir = res["logdir"]
            anchor = (res["unix_time_ns"], res["perf_counter_ns"])
        elif "already active" in str(res.get("error", "")):
            return None
    if anchor is None:  # host-only window (no jax, or device capture off)
        anchor = (time.time_ns(), time.perf_counter_ns())
    w: Dict[str, Any] = {
        "id": seq,
        "logdir": logdir,
        "anchor": anchor,
        "deadline": time.monotonic() + _state["window_s"],
        "steps": [],
        "comm": [],
        "host": [],
        "psum0": _psum_total(),
    }
    # Safety net: a loop that stops dispatching mid-window must not leave
    # the profiler slot held (the watchdog in profiling would eventually
    # force-stop it, but as an *abandoned* profile, which this is not).
    t = threading.Timer(_state["window_s"] * 4.0, _force_close, args=(seq,))
    t.daemon = True
    t.start()
    w["timer"] = t
    return w


def _discard_window(w: Dict[str, Any]) -> None:
    """Release a window that lost the install race (config changed while
    it was opening) without ingesting it."""
    timer = w.get("timer")
    if timer is not None:
        timer.cancel()
    if w["logdir"] is not None:
        profiling.stop_device_trace()


def _open_async(seq: int) -> None:
    """Open window ``seq`` off the dispatch path.  The first
    ``jax.profiler.start_trace`` of a process initialises profiler plugins
    (seconds of wall time); run synchronously inside a dispatch it would
    stall the train loop — and with it heartbeat pumping, enough to churn
    a cohort.  The window simply becomes active a moment after its
    scheduling dispatch."""
    try:
        w = _open_window(seq)
    except Exception:  # noqa: BLE001 — telemetry must never kill the loop
        _M_ERRORS.inc()
        w = None
    with _lock:
        _state["opening"] = False
        install = (
            w is not None
            and _state["interval"] > 0
            and _state["window_seq"] == seq
            and _state["window"] is None
        )
        if install:
            _state["window"] = w
    if w is not None and not install:
        _discard_window(w)


def _force_close(window_id: int) -> None:
    with _lock:
        w = _state["window"]
        if w is None or w["id"] != window_id:
            return
        _state["window"] = None
    _finish_window(w)


def _finish_window(w: Dict[str, Any]) -> None:
    # End-of-window snapshot first: stop_trace below serialises the capture
    # (up to ~1s) and must not inflate the last step's wall time.
    w["end_ns"] = time.perf_counter_ns()
    w["psum_delta"] = max(0.0, _psum_total() - w["psum0"])
    timer = w.get("timer")
    if timer is not None:
        timer.cancel()
    if w["logdir"] is not None:
        res = profiling.stop_device_trace()
        if not res.get("ok"):
            _M_ERRORS.inc()
    if not w["steps"]:
        # A window that saw no dispatches (the loop idled or ended while it
        # was opening) carries no step attribution: release the slot but
        # don't ingest — an empty report must not clobber the last real one.
        return
    t = threading.Thread(
        target=_ingest_thread, args=(w,), name="timeline-ingest", daemon=True
    )
    t.start()


def _ingest_thread(w: Dict[str, Any]) -> None:
    try:
        slices = load_profiler_trace(w["logdir"])
    except Exception:  # noqa: BLE001 — a garbled capture is one error tick
        _M_ERRORS.inc()
        slices = []
    try:
        report = ingest_window(
            w["steps"],
            comm_spans=w["comm"],
            host_spans=w["host"],
            slices=slices,
            anchor=w["anchor"],
            window_end_ns=w["end_ns"],
            psum_host_seconds=w["psum_delta"],
        )
    except Exception:  # noqa: BLE001 — attribution must never kill the loop
        _M_ERRORS.inc()
        return
    with _lock:
        _state["windows"] += 1
        _state["last_report"] = report


def on_dispatch(name: str, t0_ns: int, t1_ns: int) -> None:
    """Devmon dispatch-hook target: count instrumented dispatches, record
    them into the active window, and open/close windows on schedule.
    Opening and closing both happen on short-lived background threads —
    this path runs inside every train-step dispatch and must never block
    on the profiler (first start_trace costs seconds of plugin init,
    stop_trace serialises the capture)."""
    close = None
    open_seq = None
    with _lock:
        w = _state["window"]
        if w is not None:
            w["steps"].append((name, t0_ns, t1_ns))
            if time.monotonic() >= w["deadline"]:
                _state["window"] = None
                close = w
        elif _state["interval"] > 0 and not _state["opening"]:
            _state["calls"] += 1
            if _state["calls"] % _state["interval"] == 0:
                _state["opening"] = True
                _state["window_seq"] += 1
                open_seq = _state["window_seq"]
    if close is not None:
        threading.Thread(
            target=_finish_window, args=(close,), name="timeline-close",
            daemon=True,
        ).start()
    if open_seq is not None:
        threading.Thread(
            target=_open_async, args=(open_seq,), name="timeline-open",
            daemon=True,
        ).start()


class _PhaseSpan:
    """Records (name, t0_ns, t1_ns) into the active window's comm/host
    list; near-free when no window is open (one unlocked None check)."""

    __slots__ = ("_name", "_kind", "_t0")

    def __init__(self, name: str, kind: str):
        self._name = name
        self._kind = kind
        self._t0: Optional[int] = None

    def __enter__(self):
        if _state["window"] is not None:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            t1 = time.perf_counter_ns()
            with _lock:
                w = _state["window"]
                if w is not None:
                    w[self._kind].append((self._name, self._t0, t1))
        return False


def comm_span(name: str) -> "_PhaseSpan":
    """Mark the body as host-side collective communication for the active
    timeline window (accumulator share-down, in-mesh redistribute).  A
    no-op outside windows, so call sites wire it unconditionally."""
    return _PhaseSpan(name, "comm")


def host_span(name: str) -> "_PhaseSpan":
    """Mark the body as host-blocked device interaction (D2H fetch, infeed
    wait) for the active timeline window.  No-op outside windows."""
    return _PhaseSpan(name, "host")


def comm_mark() -> Optional[int]:
    """Timestamp (perf_counter_ns) for a later :func:`comm_interval`, or
    ``None`` when no window is open.  The pair exists for ASYNC comm whose
    begin/end straddle callbacks (the streaming gradient pipeline's
    per-bucket wire ops): the caller can't hold a ``comm_span`` context
    open across a launch→completion callback boundary, so it marks at
    launch and records retroactively at completion.  Keeps the clock choice
    inside telemetry (call sites never touch perf_counter directly)."""
    if _state["window"] is None:
        return None
    return time.perf_counter_ns()


def comm_interval(name: str, t0_ns: Optional[int],
                  t1_ns: Optional[int] = None) -> None:
    """Retroactively record ``[t0_ns, t1_ns]`` (``t1_ns`` defaults to now)
    as a comm span of the active window.  ``t0_ns=None`` (from a
    :func:`comm_mark` outside a window) is a no-op, so call sites wire the
    pair unconditionally.  Per-bucket spans may overlap each other and the
    step's compute — ``ingest_window`` unions comm spans before subtracting
    compute, so overlapping bucket ops count once, and the part concurrent
    with compute lands in ``overlapped_comm_seconds``, not exposed."""
    if t0_ns is None:
        return
    if t1_ns is None:
        t1_ns = time.perf_counter_ns()
    with _lock:
        w = _state["window"]
        if w is not None:
            w["comm"].append((name, int(t0_ns), int(t1_ns)))


def configure(
    interval: int,
    window_s: float = DEFAULT_WINDOW_S,
    device: bool = True,
) -> None:
    """Enable (interval > 0) or disable (0) periodic windows and install /
    remove the devmon dispatch hook accordingly."""
    from . import devmon

    with _lock:
        _state["interval"] = max(0, int(interval))
        _state["window_s"] = max(0.01, float(window_s))
        _state["device"] = bool(device)
        hook = on_dispatch if _state["interval"] > 0 else None
        _state["hooked"] = hook is not None
    devmon.set_dispatch_hook(hook)


def install_from_env() -> Dict[str, Any]:
    """Wire periodic windows per ``MOOLIB_TIMELINE_INTERVAL`` (dispatches
    between windows; unset/0 = off), ``MOOLIB_TIMELINE_WINDOW_S`` and
    ``MOOLIB_TIMELINE_DEVICE`` (``0`` skips the jax.profiler capture —
    host-only attribution).  Called by telemetry.init_from_env."""
    try:
        interval = int(os.environ.get("MOOLIB_TIMELINE_INTERVAL", "0") or 0)
    except ValueError:
        interval = 0
    try:
        window_s = float(
            os.environ.get("MOOLIB_TIMELINE_WINDOW_S", str(DEFAULT_WINDOW_S))
        )
    except ValueError:
        window_s = DEFAULT_WINDOW_S
    device = os.environ.get("MOOLIB_TIMELINE_DEVICE", "1") != "0"
    if interval > 0:
        configure(interval, window_s, device)
    return {"interval": interval, "window_s": window_s, "device": device}


def status() -> Dict[str, Any]:
    """Scheduler state for logs/consoles: {"interval", "window_s",
    "windows", "active", "last_report"}."""
    with _lock:
        return {
            "interval": _state["interval"],
            "window_s": _state["window_s"],
            "windows": _state["windows"],
            "active": _state["window"] is not None,
            "last_report": _state["last_report"],
        }


def reset_for_tests() -> None:
    """Close any open window without ingesting and drop scheduler state."""
    from . import devmon

    with _lock:
        w, _state["window"] = _state["window"], None
        _state.update(
            interval=0, window_s=DEFAULT_WINDOW_S, device=True, calls=0,
            opening=False, windows=0, last_report=None, hooked=False,
        )
    devmon.set_dispatch_hook(None)
    if w is not None:
        timer = w.get("timer")
        if timer is not None:
            timer.cancel()
        if w["logdir"] is not None:
            profiling.stop_device_trace()
