"""moolib_tpu.telemetry — unified metrics registry + span tracing.

The reference moolib's observability is ``debug_info`` string dumps and log
timings (SURVEY §5.1).  This package replaces that with one idiom used by
every layer of the stack (RPC transport, accumulator, envpool, batcher,
train loops)::

    from moolib_tpu import telemetry

    _REG = telemetry.get_registry()
    _STEPS = _REG.counter("envpool_steps_total", "env steps completed")
    ...
    _STEPS.inc(batch_size)

    with telemetry.span("learn"):
        ...

and exporters that read the registry without the subsystems knowing:
Prometheus text over an opt-in loopback HTTP endpoint, periodic JSONL
snapshots into the run directory, a SIGUSR1 dump handler, and Chrome
trace-event JSON of the recorded host spans (mergeable next to
``jax.profiler`` device traces).  Cohort-wide totals piggyback on the
agents' existing ``GlobalStatsAccumulator`` reduce via
:class:`CohortCounters` — no second wire protocol.

Environment knobs (read by :func:`init_from_env`, which entry points call
once; everything defaults to off):

- ``MOOLIB_TELEMETRY_HTTP_PORT`` — serve ``/metrics`` + ``/trace`` on this
  loopback port (``0`` picks a free port; the chosen one is logged).
- ``MOOLIB_TELEMETRY_DIR`` — run directory for periodic JSONL snapshots
  (``telemetry.jsonl``) and the final host Chrome trace
  (``host_trace.json``).
- ``MOOLIB_TELEMETRY_INTERVAL`` — JSONL snapshot period, seconds
  (default 15).
- ``MOOLIB_TELEMETRY_SIGUSR1`` — ``0`` disables the dump-on-signal
  handler (installed by default when ``init_from_env`` runs on the main
  thread).
- ``MOOLIB_DEVMON_INTERVAL`` / ``MOOLIB_DEVMON_HBM_WARN_FRACTION`` —
  device performance plane knobs (:mod:`moolib_tpu.telemetry.devmon`).
- ``MOOLIB_TIMELINE_INTERVAL`` / ``MOOLIB_TIMELINE_WINDOW_S`` — periodic
  fused host+device overlap capture windows
  (:mod:`moolib_tpu.telemetry.timeline`).

The metric name reference lives in docs/TELEMETRY.md.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    DEFAULT_SIZE_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    get_registry,
)
from .tracing import (  # noqa: F401
    Span,
    TraceContext,
    Tracer,
    attach_context,
    child_span,
    current_context,
    decode_context,
    encode_context,
    get_tracer,
    root_span,
    span,
)
from .exporters import (  # noqa: F401
    JsonlSnapshotter,
    dump_diagnostics,
    install_signal_dump,
    prometheus_text,
    read_snapshot_tail,
    serve_http,
)
from .flightrec import (  # noqa: F401
    FlightRecorder,
    flight_event,
    get_flight_recorder,
)
from .cohort import CohortCounters  # noqa: F401
from .aggregator import CohortAggregator, install_rpc_handlers  # noqa: F401
from . import devmon  # noqa: F401
from . import profiling  # noqa: F401
from . import timeline  # noqa: F401
from .recovery import (  # noqa: F401
    RECOVERY_BUCKETS,
    RECOVERY_PHASES,
    observe_phase,
    recovery_histogram,
)

__all__ = [
    "CohortAggregator",
    "CohortCounters",
    "install_rpc_handlers",
    "profiling",
    "RECOVERY_BUCKETS",
    "RECOVERY_PHASES",
    "observe_phase",
    "recovery_histogram",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlSnapshotter",
    "Registry",
    "Span",
    "TraceContext",
    "Tracer",
    "attach_context",
    "child_span",
    "current_context",
    "decode_context",
    "devmon",
    "dump_diagnostics",
    "encode_context",
    "flight_event",
    "flush",
    "get_flight_recorder",
    "get_registry",
    "get_tracer",
    "init_from_env",
    "install_signal_dump",
    "read_snapshot_tail",
    "root_span",
    "shutdown",
    "prometheus_text",
    "serve_http",
    "span",
    "timeline",
]

_init_lock = threading.Lock()
_initialized = False
_snapshotter: Optional[JsonlSnapshotter] = None
_http_port: Optional[int] = None


def init_from_env() -> dict:
    """Start the exporters the environment asks for (see module docstring).

    Idempotent — entry points and libraries can all call it; only the first
    call starts anything.  Returns ``{"http_port": int|None, "run_dir":
    str|None}`` for logging."""
    global _initialized, _snapshotter, _http_port
    with _init_lock:
        if _initialized:
            return {"http_port": _http_port, "run_dir": _snapshotter._dir if _snapshotter else None}
        _initialized = True
        # Every failure below degrades to "that exporter is off" with a
        # stderr note — a malformed observability knob must never kill a
        # training entry point at startup.
        run_dir = os.environ.get("MOOLIB_TELEMETRY_DIR") or None
        port_s = os.environ.get("MOOLIB_TELEMETRY_HTTP_PORT")
        if port_s is not None:
            try:
                _http_port = serve_http(int(port_s))
            except (OSError, ValueError) as e:
                _http_port = None
                _warn(f"http exporter disabled ({e!r})")
        if run_dir:
            try:
                interval = float(os.environ.get("MOOLIB_TELEMETRY_INTERVAL", "15"))
            except ValueError as e:
                interval = 15.0
                _warn(f"bad MOOLIB_TELEMETRY_INTERVAL ({e!r}); using 15s")
            try:
                _snapshotter = JsonlSnapshotter(run_dir, interval=interval)
                # Runs shorter than one interval still get their final
                # snapshot + host trace; an earlier explicit shutdown()
                # makes this a no-op.
                atexit.register(shutdown)
            except OSError as e:
                run_dir = None
                _warn(f"jsonl exporter disabled ({e!r})")
        if os.environ.get("MOOLIB_TELEMETRY_SIGUSR1", "1") != "0":
            install_signal_dump(run_dir)
        try:
            # Device performance plane: jax.monitoring compile listeners
            # (only when jax is already in the process) and the optional
            # periodic HBM sampler (MOOLIB_DEVMON_INTERVAL).
            devmon.install_from_env()
        except Exception as e:  # noqa: BLE001 — same degradation contract
            _warn(f"devmon disabled ({e!r})")
        try:
            # Fused host+device step timeline: periodic overlap/exposure
            # capture windows (MOOLIB_TIMELINE_INTERVAL; off by default).
            timeline.install_from_env()
        except Exception as e:  # noqa: BLE001 — same degradation contract
            _warn(f"timeline disabled ({e!r})")
        return {"http_port": _http_port, "run_dir": run_dir}


def _warn(msg: str) -> None:
    import sys

    sys.stderr.write(f"moolib_tpu.telemetry: {msg}\n")


def flush() -> None:
    """Write a JSONL snapshot + host trace now, keeping the exporters
    running.  Entry points call this at the end of train() — a second
    train() in the same process keeps its telemetry (shutdown() would
    permanently disable the snapshotter while init_from_env stays latched).
    """
    with _init_lock:
        snap = _snapshotter
    if snap is not None:
        snap.flush()


def shutdown() -> None:
    """Stop the JSONL snapshotter after a final snapshot + host trace.
    Registered atexit by init_from_env; daemon threads die with the
    process either way."""
    global _snapshotter
    with _init_lock:
        snap, _snapshotter = _snapshotter, None
        if snap is not None:
            snap.close()
