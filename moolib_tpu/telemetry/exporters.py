"""Exporters: Prometheus text exposition, HTTP endpoint, JSONL snapshots,
dump-on-SIGUSR1.

All opt-in and stdlib-only.  The usual wiring is one
:func:`moolib_tpu.telemetry.init_from_env` call at the top of a training
entry point; each exporter can also be driven directly:

- :func:`prometheus_text` — the registry in Prometheus text exposition
  format 0.0.4 (counters, gauges, histograms with ``_bucket/_sum/_count``).
- :func:`serve_http` — a daemon-thread ``http.server`` answering
  ``/metrics`` (Prometheus text) and ``/trace`` (Chrome trace JSON).
- :class:`JsonlSnapshotter` — periodic one-line JSON snapshots of every
  metric family appended to ``<dir>/telemetry.jsonl`` (plus a final Chrome
  trace at ``close()``), for offline rate computation when no scraper runs.
- :func:`install_signal_dump` — SIGUSR1 prints the Prometheus text (and
  writes the Chrome trace when a run dir is known): kick a live process for
  its counters without attaching anything.
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import threading
import time
import traceback
from typing import Optional

from .metrics import Registry, get_registry
from .tracing import Tracer, get_tracer
from .flightrec import format_tail as _flight_tail

__all__ = [
    "prometheus_text",
    "serve_http",
    "JsonlSnapshotter",
    "dump_diagnostics",
    "install_signal_dump",
    "read_snapshot_tail",
]


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: dict, extra: Optional[tuple] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items = items + [extra]
    if not items:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n"))
        for k, v in items
    )
    return "{%s}" % inner


def prometheus_text(registry: Optional[Registry] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of every registered
    metric.  Histograms render cumulative ``_bucket{le=...}`` series plus
    ``_sum``/``_count``, as scrapers expect."""
    registry = registry or get_registry()
    lines = []
    for m in registry.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            for labels, h in m.samples():
                cum = 0
                for bound, n in zip(m.buckets, h["buckets"]):
                    cum += n
                    lines.append(
                        f"{m.name}_bucket{_fmt_labels(labels, ('le', _fmt_value(bound)))} {cum}"
                    )
                cum += h["buckets"][-1]
                lines.append(f"{m.name}_bucket{_fmt_labels(labels, ('le', '+Inf'))} {cum}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)} {_fmt_value(h['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)} {h['count']}")
        else:
            for labels, v in m.samples():
                lines.append(f"{m.name}{_fmt_labels(labels)} {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


def serve_http(
    port: int = 0,
    host: str = "127.0.0.1",
    registry: Optional[Registry] = None,
    tracer: Optional[Tracer] = None,
) -> int:
    """Serve ``/metrics`` (Prometheus text) and ``/trace`` (Chrome trace
    JSON) from a daemon thread; returns the bound port (``port=0`` picks a
    free one).  Loopback by default — exposing beyond the host is a
    deployment decision, not a library default."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    registry = registry or get_registry()
    tracer = tracer or get_tracer()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path.split("?")[0] == "/metrics":
                body = prometheus_text(registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/trace":
                body = json.dumps(tracer.chrome_trace()).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, name="telemetry-http", daemon=True)
    t.start()
    return server.server_address[1]


class JsonlSnapshotter:
    """Append one JSON line of the full registry snapshot to
    ``<run_dir>/telemetry.jsonl`` every ``interval`` seconds (daemon
    thread); ``close()`` writes a final snapshot plus the Chrome trace to
    ``<run_dir>/host_trace.json``.  Rates are computed offline from
    consecutive counter snapshots, so no scraper needs to be running."""

    def __init__(
        self,
        run_dir: str,
        interval: float = 15.0,
        registry: Optional[Registry] = None,
        tracer: Optional[Tracer] = None,
    ):
        self._registry = registry or get_registry()
        self._tracer = tracer or get_tracer()
        self._dir = run_dir
        self._path = os.path.join(run_dir, "telemetry.jsonl")
        self._interval = float(interval)
        self._stop = threading.Event()
        os.makedirs(run_dir, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="telemetry-jsonl", daemon=True
        )
        self._thread.start()

    def snapshot_now(self) -> None:
        row = {
            "time": time.time(),
            "pid": os.getpid(),
            "metrics": self._registry.snapshot(),
        }
        with open(self._path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.snapshot_now()
            except OSError:
                return  # run dir vanished; stop quietly

    def flush(self) -> None:
        """Write a snapshot + the host Chrome trace now, without stopping
        the periodic thread (end-of-run flush; the process may train again)."""
        try:
            self.snapshot_now()
            self._tracer.export_chrome_trace(os.path.join(self._dir, "host_trace.json"))
        except OSError:
            pass

    def close(self) -> None:
        self._stop.set()
        self.flush()


def read_snapshot_tail(path: str, max_bytes: int = 1 << 20):
    """Last parseable JSONL snapshot in ``path`` (None if absent/empty) —
    the reader counterpart of :class:`JsonlSnapshotter`, shared by the
    autoscaler's file-tail sampling and the cohort aggregator's fallbacks.
    Reads only the file tail: snapshot files grow for the process lifetime,
    and a half-written final line (snapshotter racing us) falls back to the
    previous complete one."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            snap = json.loads(line)
        except ValueError:
            continue
        if isinstance(snap, dict) and "metrics" in snap:
            return snap
    return None


def dump_diagnostics(
    reason: str = "",
    run_dir: Optional[str] = None,
    registry: Optional[Registry] = None,
    tracer: Optional[Tracer] = None,
    file=None,
    stacks: bool = True,
) -> None:
    """One-stop diagnostic dump shared by the SIGUSR1 handler and the
    run-loop watchdog (:mod:`moolib_tpu.watchdog`): the registry in
    Prometheus text, the python stack of every live thread (wedge triage:
    *where* is each thread blocked?), and — when a run dir is known — the
    host Chrome trace.  Only formats already-collected data, so it is safe
    from a signal handler or a monitor thread."""
    registry = registry or get_registry()
    tracer = tracer or get_tracer()
    out = file or sys.stderr
    header = f"pid {os.getpid()}" + (f", {reason}" if reason else "")
    parts = [f"--- telemetry dump ({header}) ---\n", prometheus_text(registry)]
    if stacks:
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            parts.append(f"--- thread {names.get(tid, '?')!r} (ident {tid}) ---\n")
            parts.append("".join(traceback.format_stack(frame)))
    # The flight recorder's recent-event tail: what the process believed
    # was happening right before the dump (watchdog expiry, crash, signal).
    parts.append(_flight_tail())
    # Lock-order graph (MOOLIB_LOCKGRAPH=1): observed acquisition-order
    # cycles with both offending stacks, plus long-hold outliers.
    try:
        from ..testing import lockgraph as _lockgraph

        parts.append(_lockgraph.diagnostics_tail())
    except Exception:  # noqa: BLE001 — diagnostics must never throw
        pass
    # Device performance plane: HBM watermarks, compile counts, and the
    # last recompile signature diff — the "why is the hardware idle" tail.
    try:
        from . import devmon as _devmon

        parts.append(_devmon.summary_text())
    except Exception:  # noqa: BLE001 — diagnostics must never throw
        pass
    parts.append("--- end telemetry dump ---\n")
    out.write("".join(parts))
    try:
        out.flush()
    except OSError:
        pass
    if run_dir:
        try:
            tracer.export_chrome_trace(os.path.join(run_dir, "host_trace.json"))
        except OSError:
            pass


_signal_installed = False


def install_signal_dump(
    run_dir: Optional[str] = None,
    registry: Optional[Registry] = None,
    tracer: Optional[Tracer] = None,
    signum: int = signal.SIGUSR1,
) -> bool:
    """SIGUSR1 → :func:`dump_diagnostics` to stderr (metrics + thread
    stacks, plus the Chrome trace into ``run_dir`` when given).  Main
    thread only (CPython restriction); returns False when the handler
    could not be installed."""
    global _signal_installed
    registry = registry or get_registry()
    tracer = tracer or get_tracer()

    def _dump(sig, frame):
        dump_diagnostics(
            reason=f"signal {sig}", run_dir=run_dir, registry=registry, tracer=tracer
        )

    try:
        signal.signal(signum, _dump)
    except (ValueError, OSError):  # not the main thread, or unsupported
        return False
    _signal_installed = True
    return True
