"""On-demand ``jax.profiler`` device-trace windows, RPC- or signal-driven.

A device profile is the one observability surface you cannot leave running:
it costs memory and perturbs timing.  This module makes it a *window* you
open remotely on a live process — over the ``__telemetry_profile`` RPC
every scrapable peer defines (:func:`moolib_tpu.telemetry.aggregator
.install_rpc_handlers`), or a local signal toggle — and closes either
explicitly or after a timed duration.

Each window records a ``device_profile`` span in the host tracer when it
closes, with the same ``perf_counter_ns`` clock every other span uses, so a
merged cohort timeline (``scripts/trace_merge.py``) shows exactly which
host-side work the device capture brackets; the returned anchors
(``unix_time_ns``/``perf_counter_ns`` at start) let offline tooling align
the XLA trace the same way.

``jax`` is imported lazily inside the start path only — processes that
never profile (env workers, the broker) never pay the import, and a box
without jax degrades to an error dict instead of an exception.
"""

from __future__ import annotations

import os
import signal as _signal
import threading
import time
from typing import Optional

from . import tracing
from .flightrec import flight_event

__all__ = [
    "start_device_trace",
    "stop_device_trace",
    "profile_status",
    "handle_command",
    "install_signal_toggle",
]

_lock = threading.Lock()
_active: Optional[dict] = None  # {"logdir", "t0_ns", "unix_ns", "timer", "guard"}

DEFAULT_WINDOW_S = 3.0
# Hard ceiling on any window's lifetime.  The stop is normally an RPC from
# the requester; a requester killed mid-window would otherwise leave the
# profiler armed forever (collecting, costing memory, blocking every later
# start with "profile already active").  MOOLIB_PROFILE_MAX_WINDOW_S
# overrides; <= 0 disables the guard.
DEFAULT_MAX_WINDOW_S = 120.0


def _max_window_s() -> float:
    try:
        return float(
            os.environ.get("MOOLIB_PROFILE_MAX_WINDOW_S", str(DEFAULT_MAX_WINDOW_S))
        )
    except ValueError:
        return DEFAULT_MAX_WINDOW_S


def _arm_guard(logdir: str, max_s: float):
    """Watchdog-fed deadline that force-stops an abandoned window.  Returns
    the guard object to close on a normal stop; None when disabled.  Uses
    the repo Watchdog (lazy import — watchdog.py imports telemetry) with a
    plain daemon Timer as fallback so the ceiling survives either way."""

    def _expire(_section: str, timeout: float) -> None:
        with _lock:
            abandoned = _active is not None and _active["logdir"] == logdir
        if not abandoned:
            return  # the window was stopped (and maybe another opened) in time
        flight_event("profile.abandoned", logdir=logdir, max_window_s=timeout)
        tracing.get_tracer().event(
            "device_profile.abandoned", logdir=logdir, max_window_s=timeout
        )
        stop_device_trace()

    try:
        from ..watchdog import Watchdog

        wd = Watchdog(
            timeout=max_s, on_expire=_expire, name="profile-window", dump=False
        )
        wd.arm("device_profile", max_s)
        return wd
    except Exception:  # noqa: BLE001 — guard must not block the profile itself
        timer = threading.Timer(max_s, _expire, args=("device_profile", max_s))
        timer.daemon = True
        timer.start()
        return timer


def _close_guard(guard) -> None:
    if guard is None:
        return
    try:
        guard.close()  # Watchdog
    except AttributeError:
        guard.cancel()  # Timer fallback


def _default_logdir() -> str:
    base = os.environ.get("MOOLIB_PROFILE_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "moolib_profiles"
    )
    return os.path.join(base, f"pid{os.getpid()}-{int(time.time())}")


def start_device_trace(logdir: Optional[str] = None) -> dict:
    """Open a ``jax.profiler`` trace window.  Returns ``{"ok": True,
    "logdir", "unix_time_ns", "perf_counter_ns"}`` (the anchors match the
    host tracer's clock) or ``{"ok": False, "error"}`` — never raises, so
    the RPC handler can always serialize the answer."""
    global _active
    with _lock:
        if _active is not None:
            return {"ok": False, "error": "profile already active", "logdir": _active["logdir"]}
        logdir = logdir or _default_logdir()
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except ImportError:
            return {"ok": False, "error": "jax unavailable"}
        except Exception as e:  # noqa: BLE001 — report, don't kill the peer
            return {"ok": False, "error": f"start_trace failed: {e}"}
        _active = {
            "logdir": logdir,
            "t0_ns": time.perf_counter_ns(),
            "unix_ns": time.time_ns(),
            "timer": None,
            "guard": None,
        }
        tracing.get_tracer().event("device_profile.start", logdir=logdir)
        anchors = {
            "ok": True,
            "logdir": logdir,
            "unix_time_ns": _active["unix_ns"],
            "perf_counter_ns": _active["t0_ns"],
        }
    # Outside the lock: the guard's expiry path calls stop_device_trace,
    # and Watchdog construction must not run under _lock.
    max_s = _max_window_s()
    if max_s > 0:
        guard = _arm_guard(logdir, max_s)
        with _lock:
            if _active is not None and _active["logdir"] == logdir:
                _active["guard"] = guard
            else:  # stopped already (tiny window) — don't leak the monitor
                _close_guard(guard)
    return anchors


def stop_device_trace() -> dict:
    """Close the active window; records the ``device_profile`` host span
    covering it."""
    global _active
    err = None
    with _lock:
        if _active is None:
            return {"ok": False, "error": "no profile active"}
        state, _active = _active, None
        timer = state.get("timer")
        if timer is not None:
            timer.cancel()
        try:
            import jax

            jax.profiler.stop_trace()
        except ImportError:
            err = {"ok": False, "error": "jax unavailable"}
        except Exception as e:  # noqa: BLE001
            err = {"ok": False, "error": f"stop_trace failed: {e}", "logdir": state["logdir"]}
    # Outside the lock: closing the guard may join its monitor thread, whose
    # expiry path takes _lock.
    _close_guard(state.get("guard"))
    if err is not None:
        return err
    dur_ns = time.perf_counter_ns() - state["t0_ns"]
    tracing.get_tracer().record(
        "device_profile",
        state["t0_ns"],
        dur_ns,
        args={"logdir": state["logdir"]},
    )
    return {"ok": True, "logdir": state["logdir"], "duration_s": dur_ns / 1e9}


def profile_status() -> dict:
    with _lock:
        if _active is None:
            return {"active": False}
        return {"active": True, "logdir": _active["logdir"]}


def handle_command(
    action: str, logdir: Optional[str] = None, seconds: Optional[float] = None
) -> dict:
    """The ``__telemetry_profile`` RPC surface:

    - ``"start"`` — open a window (until an explicit stop).
    - ``"stop"`` — close it.
    - ``"status"`` — is one open, and where.
    - ``"window"`` — open and auto-close after ``seconds``
      (default :data:`DEFAULT_WINDOW_S`); the follow-up stop runs on a
      daemon timer, so the requesting client doesn't have to stay alive.
    """
    if action == "start":
        return start_device_trace(logdir)
    if action == "stop":
        return stop_device_trace()
    if action == "status":
        return profile_status()
    if action == "window":
        res = start_device_trace(logdir)
        if not res.get("ok"):
            return res
        delay = DEFAULT_WINDOW_S if seconds is None else max(0.1, float(seconds))
        timer = threading.Timer(delay, stop_device_trace)
        timer.daemon = True
        with _lock:
            if _active is not None:
                _active["timer"] = timer
        timer.start()
        res["window_s"] = delay
        return res
    return {"ok": False, "error": f"unknown action {action!r}"}


def install_signal_toggle(
    signum: int = _signal.SIGUSR2, logdir: Optional[str] = None
) -> bool:
    """Toggle a device-trace window on ``signum`` (default SIGUSR2 — the
    SIGUSR1 slot belongs to the diagnostics dump).  Main thread only;
    returns False when the handler could not be installed."""

    def _toggle(sig, frame):
        if profile_status()["active"]:
            stop_device_trace()
        else:
            start_device_trace(logdir)

    try:
        _signal.signal(signum, _toggle)
    except (ValueError, OSError):
        return False
    return True
