"""On-demand ``jax.profiler`` device-trace windows, RPC- or signal-driven.

A device profile is the one observability surface you cannot leave running:
it costs memory and perturbs timing.  This module makes it a *window* you
open remotely on a live process — over the ``__telemetry_profile`` RPC
every scrapable peer defines (:func:`moolib_tpu.telemetry.aggregator
.install_rpc_handlers`), or a local signal toggle — and closes either
explicitly or after a timed duration.

Each window records a ``device_profile`` span in the host tracer when it
closes, with the same ``perf_counter_ns`` clock every other span uses, so a
merged cohort timeline (``scripts/trace_merge.py``) shows exactly which
host-side work the device capture brackets; the returned anchors
(``unix_time_ns``/``perf_counter_ns`` at start) let offline tooling align
the XLA trace the same way.

``jax`` is imported lazily inside the start path only — processes that
never profile (env workers, the broker) never pay the import, and a box
without jax degrades to an error dict instead of an exception.
"""

from __future__ import annotations

import os
import signal as _signal
import threading
import time
from typing import Optional

from . import tracing

__all__ = [
    "start_device_trace",
    "stop_device_trace",
    "profile_status",
    "handle_command",
    "install_signal_toggle",
]

_lock = threading.Lock()
_active: Optional[dict] = None  # {"logdir", "t0_ns", "unix_ns", "timer"}

DEFAULT_WINDOW_S = 3.0


def _default_logdir() -> str:
    base = os.environ.get("MOOLIB_PROFILE_DIR") or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "moolib_profiles"
    )
    return os.path.join(base, f"pid{os.getpid()}-{int(time.time())}")


def start_device_trace(logdir: Optional[str] = None) -> dict:
    """Open a ``jax.profiler`` trace window.  Returns ``{"ok": True,
    "logdir", "unix_time_ns", "perf_counter_ns"}`` (the anchors match the
    host tracer's clock) or ``{"ok": False, "error"}`` — never raises, so
    the RPC handler can always serialize the answer."""
    global _active
    with _lock:
        if _active is not None:
            return {"ok": False, "error": "profile already active", "logdir": _active["logdir"]}
        logdir = logdir or _default_logdir()
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except ImportError:
            return {"ok": False, "error": "jax unavailable"}
        except Exception as e:  # noqa: BLE001 — report, don't kill the peer
            return {"ok": False, "error": f"start_trace failed: {e}"}
        _active = {
            "logdir": logdir,
            "t0_ns": time.perf_counter_ns(),
            "unix_ns": time.time_ns(),
            "timer": None,
        }
        tracing.get_tracer().event("device_profile.start", logdir=logdir)
        return {
            "ok": True,
            "logdir": logdir,
            "unix_time_ns": _active["unix_ns"],
            "perf_counter_ns": _active["t0_ns"],
        }


def stop_device_trace() -> dict:
    """Close the active window; records the ``device_profile`` host span
    covering it."""
    global _active
    with _lock:
        if _active is None:
            return {"ok": False, "error": "no profile active"}
        state, _active = _active, None
        timer = state.get("timer")
        if timer is not None:
            timer.cancel()
        try:
            import jax

            jax.profiler.stop_trace()
        except ImportError:
            return {"ok": False, "error": "jax unavailable"}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": f"stop_trace failed: {e}", "logdir": state["logdir"]}
    dur_ns = time.perf_counter_ns() - state["t0_ns"]
    tracing.get_tracer().record(
        "device_profile",
        state["t0_ns"],
        dur_ns,
        args={"logdir": state["logdir"]},
    )
    return {"ok": True, "logdir": state["logdir"], "duration_s": dur_ns / 1e9}


def profile_status() -> dict:
    with _lock:
        if _active is None:
            return {"active": False}
        return {"active": True, "logdir": _active["logdir"]}


def handle_command(
    action: str, logdir: Optional[str] = None, seconds: Optional[float] = None
) -> dict:
    """The ``__telemetry_profile`` RPC surface:

    - ``"start"`` — open a window (until an explicit stop).
    - ``"stop"`` — close it.
    - ``"status"`` — is one open, and where.
    - ``"window"`` — open and auto-close after ``seconds``
      (default :data:`DEFAULT_WINDOW_S`); the follow-up stop runs on a
      daemon timer, so the requesting client doesn't have to stay alive.
    """
    if action == "start":
        return start_device_trace(logdir)
    if action == "stop":
        return stop_device_trace()
    if action == "status":
        return profile_status()
    if action == "window":
        res = start_device_trace(logdir)
        if not res.get("ok"):
            return res
        delay = DEFAULT_WINDOW_S if seconds is None else max(0.1, float(seconds))
        timer = threading.Timer(delay, stop_device_trace)
        timer.daemon = True
        with _lock:
            if _active is not None:
                _active["timer"] = timer
        timer.start()
        res["window_s"] = delay
        return res
    return {"ok": False, "error": f"unknown action {action!r}"}


def install_signal_toggle(
    signum: int = _signal.SIGUSR2, logdir: Optional[str] = None
) -> bool:
    """Toggle a device-trace window on ``signum`` (default SIGUSR2 — the
    SIGUSR1 slot belongs to the diagnostics dump).  Main thread only;
    returns False when the handler could not be installed."""

    def _toggle(sig, frame):
        if profile_status()["active"]:
            stop_device_trace()
        else:
            start_device_trace(logdir)

    try:
        _signal.signal(signum, _toggle)
    except (ValueError, OSError):
        return False
    return True
