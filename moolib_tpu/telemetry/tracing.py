"""Lightweight host-side span tracer with Chrome trace-event export.

``jax.profiler`` owns the *device* timeline (XLA execution, HBM, ICI); this
tracer owns the *host* side: nested spans around the train loop's act/learn/
reduce phases, RPC rounds, env waits.  Spans export as Chrome trace-event
JSON (``chrome://tracing`` / Perfetto "Complete" events), so a host trace
can sit next to a ``jax.profiler`` capture — and when a jax trace is active
and annotations are enabled, each span also enters a
``jax.profiler.TraceAnnotation`` so the same names appear inside the device
timeline (the merge path :func:`moolib_tpu.utils.profiling.annotate`
documents).

Recording is bounded (a ring of the newest ``capacity`` spans) and cheap:
one ``perf_counter_ns`` pair plus a deque append per span; nesting depth is
tracked per-thread with no locks on the hot path.  Stdlib only unless
annotations are switched on.

Distributed tracing
-------------------
Spans optionally carry W3C-style identity — a 128-bit ``trace_id`` shared
by every span of one logical operation and a 64-bit ``span_id`` unique per
span, with ``parent_id`` naming the span that caused it.  A per-thread
context stack links them up:

* :func:`root_span` starts a fresh trace (new ``trace_id``) and pushes it.
* Plain :func:`span` joins the active trace when one is on this thread's
  stack (its parent is the enclosing span) and stays id-free otherwise, so
  untraced code pays nothing and emits unchanged events.
* :func:`child_span` continues a trace whose context arrived from another
  process — the RPC layer decodes 24 bytes off the call frame
  (:func:`decode_context`) and opens the handler under it, which is what
  makes one serve request or one gradient round a single causal tree
  across hosts.  ``scripts/trace_merge.py`` stitches the per-process
  exports back together using each file's ``metadata.clock_sync`` anchor.
"""

from __future__ import annotations

import contextlib
import json
import os
import struct
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "Span",
    "TraceContext",
    "Tracer",
    "get_tracer",
    "span",
    "root_span",
    "child_span",
    "attach_context",
    "current_context",
    "encode_context",
    "decode_context",
    "new_trace_id",
    "new_span_id",
    "CONTEXT_WIRE_LEN",
]

# Wire form of a TraceContext: 16-byte trace_id + 8-byte span_id, little
# endian.  The RPC request header carries this blob (or nothing at all when
# no trace is active — untraced calls stay byte-identical in cost).
CONTEXT_WIRE_LEN = 24
_CTX_STRUCT = struct.Struct("<16s8s")


def new_trace_id() -> int:
    """Random non-zero 128-bit trace id."""
    while True:
        v = int.from_bytes(os.urandom(16), "little")
        if v:
            return v


def new_span_id() -> int:
    """Random non-zero 64-bit span id."""
    while True:
        v = int.from_bytes(os.urandom(8), "little")
        if v:
            return v


class TraceContext:
    """Identity of the *current* span: which trace, which span.  Immutable;
    what rides the wire and the per-thread stack."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self) -> "TraceContext":
        """A fresh context in the same trace (new span id)."""
        return TraceContext(self.trace_id, new_span_id())

    def __eq__(self, other):
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __hash__(self):
        return hash((self.trace_id, self.span_id))

    def __repr__(self):
        return f"TraceContext(trace_id={self.trace_id:032x}, span_id={self.span_id:016x})"


def encode_context(ctx: Optional[TraceContext]) -> bytes:
    """24-byte wire form (empty bytes for ``None`` — zero frame overhead)."""
    if ctx is None:
        return b""
    return _CTX_STRUCT.pack(
        ctx.trace_id.to_bytes(16, "little"), ctx.span_id.to_bytes(8, "little")
    )


def decode_context(data: bytes) -> Optional[TraceContext]:
    """Inverse of :func:`encode_context`; ``None`` on empty/odd-sized/zero
    input rather than raising (a peer speaking a future layout must not
    break request handling)."""
    if len(data) != CONTEXT_WIRE_LEN:
        return None
    tb, sb = _CTX_STRUCT.unpack(data)
    trace_id = int.from_bytes(tb, "little")
    span_id = int.from_bytes(sb, "little")
    if not trace_id or not span_id:
        return None
    return TraceContext(trace_id, span_id)


_tls = threading.local()


def _ctx_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = []
        _tls.stack = stack
    return stack


def current_context() -> Optional[TraceContext]:
    """The innermost active trace context on this thread, or ``None``."""
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return None


@contextlib.contextmanager
def attach_context(ctx: Optional[TraceContext]):
    """Make ``ctx`` the ambient context for the body WITHOUT opening a new
    span — for resuming a logical operation's identity on another thread
    (the serve client's retry timers fire attempts long after ``submit``
    returned) so calls made inside parent under a span that is recorded
    manually at completion.  ``None`` is a no-op."""
    if ctx is None:
        yield
        return
    stack = _ctx_stack()
    stack.append(ctx)
    try:
        yield
    finally:
        if stack and stack[-1] is ctx:
            stack.pop()
        else:  # mismatched nesting — drop ours wherever it landed
            try:
                stack.remove(ctx)
            except ValueError:
                pass


class Span:
    """One closed span: name, start (ns since epoch-ish origin), duration.

    ``trace_id``/``span_id``/``parent_id`` are ``None`` for spans recorded
    outside any trace; ``dur_ns`` is ``None`` for instant events."""

    __slots__ = (
        "name",
        "start_ns",
        "dur_ns",
        "tid",
        "thread_name",
        "args",
        "trace_id",
        "span_id",
        "parent_id",
    )

    def __init__(
        self,
        name,
        start_ns,
        dur_ns,
        tid,
        thread_name,
        args,
        trace_id=None,
        span_id=None,
        parent_id=None,
    ):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.thread_name = thread_name
        self.args = args
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id


# _ActiveSpan trace modes: join the ambient context if any (plain span()),
# force a fresh trace (root_span), or continue an explicit remote parent
# (child_span).
_AUTO, _ROOT, _CHILD = 0, 1, 2


class _ActiveSpan:
    __slots__ = (
        "_tracer",
        "_name",
        "_args",
        "_t0",
        "_annotation",
        "_mode",
        "_parent_ctx",
        "_ctx",
        "_parent_id",
        "_pushed",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        args: Optional[dict],
        mode: int = _AUTO,
        parent_ctx: Optional[TraceContext] = None,
    ):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._annotation = None
        self._mode = mode
        self._parent_ctx = parent_ctx
        self._ctx = None
        self._parent_id = None
        self._pushed = False

    @property
    def context(self) -> Optional[TraceContext]:
        """This span's TraceContext while open (``None`` when untraced)."""
        return self._ctx

    def __enter__(self):
        if self._mode == _ROOT:
            self._ctx = TraceContext(new_trace_id(), new_span_id())
        elif self._mode == _CHILD:
            parent = self._parent_ctx
            if parent is not None:
                self._parent_id = parent.span_id
                self._ctx = parent.child()
        else:
            parent = current_context()
            if parent is not None:
                self._parent_id = parent.span_id
                self._ctx = parent.child()
        if self._ctx is not None:
            _ctx_stack().append(self._ctx)
            self._pushed = True
        if self._tracer._annotate:
            ann = _jax_annotation(self._name)
            if ann is not None:
                ann.__enter__()
                self._annotation = ann
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
        if self._pushed:
            stack = _ctx_stack()
            if stack and stack[-1] is self._ctx:
                stack.pop()
            else:  # mismatched enter/exit ordering — drop ours wherever it is
                try:
                    stack.remove(self._ctx)
                except ValueError:
                    pass
            self._pushed = False
        ctx = self._ctx
        t = threading.current_thread()
        self._tracer._spans.append(
            Span(
                self._name,
                self._t0,
                dur,
                t.ident or 0,
                t.name,
                self._args,
                ctx.trace_id if ctx is not None else None,
                ctx.span_id if ctx is not None else None,
                self._parent_id,
            )
        )
        return False


def _jax_annotation(name: str):
    """A jax TraceAnnotation when jax is already imported; never imports it
    (the tracer must stay usable in env workers that never touch jax)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — annotation is best-effort decoration
        return None


class Tracer:
    """Bounded span recorder.  ``get_tracer()`` returns the process default."""

    def __init__(self, capacity: int = 65536):
        self._spans: deque = deque(maxlen=capacity)
        self._annotate = False
        # Anchor pairing the monotonic span clock to wall time, captured
        # once: lets trace_merge rebase every process onto one unix-time
        # axis (perf_counter origins are arbitrary per process).
        self._clock_anchor = (time.time_ns(), time.perf_counter_ns())

    def span(self, name: str, **args) -> _ActiveSpan:
        """Context manager recording one span; nest freely (the Chrome view
        reconstructs nesting from same-thread containment).  Joins the
        thread's active trace when one exists, else records id-free."""
        return _ActiveSpan(self, name, args or None)

    def root_span(self, name: str, **args) -> _ActiveSpan:
        """Open a span that STARTS a new trace — the entry point of a
        logical operation (a serve request, one ``reduce_gradients`` round).
        Everything recorded beneath it, on any host the RPC layer carries
        the context to, shares its ``trace_id``."""
        return _ActiveSpan(self, name, args or None, mode=_ROOT)

    def child_span(
        self, name: str, parent: Optional[TraceContext], **args
    ) -> _ActiveSpan:
        """Open a span under an explicit parent context (typically decoded
        off an RPC frame).  ``parent=None`` degrades to a plain span."""
        mode = _CHILD if parent is not None else _AUTO
        return _ActiveSpan(self, name, args or None, mode=mode, parent_ctx=parent)

    def record(
        self,
        name: str,
        start_ns: int,
        dur_ns: int,
        trace_id: Optional[int] = None,
        span_id: Optional[int] = None,
        parent_id: Optional[int] = None,
        args: Optional[dict] = None,
    ) -> None:
        """Append an already-timed span — for code that cannot hold a
        context manager open (the RPC client records its ``rpc.call`` span
        when the response future resolves, possibly on another thread)."""
        t = threading.current_thread()
        self._spans.append(
            Span(
                name,
                start_ns,
                dur_ns,
                t.ident or 0,
                t.name,
                args or None,
                trace_id,
                span_id,
                parent_id,
            )
        )

    def event(self, name: str, **args) -> None:
        """Record an instant event (zero-duration marker) at now, tagged
        with the active trace context if any."""
        ctx = current_context()
        t = threading.current_thread()
        self._spans.append(
            Span(
                name,
                time.perf_counter_ns(),
                None,
                t.ident or 0,
                t.name,
                args or None,
                ctx.trace_id if ctx is not None else None,
                ctx.span_id if ctx is not None else None,
                None,
            )
        )

    def enable_jax_annotations(self, enabled: bool = True) -> None:
        """Mirror every span into ``jax.profiler.TraceAnnotation`` so host
        phases appear inside device traces.  Off by default: creating an
        annotation per span costs even when no device trace is running."""
        self._annotate = bool(enabled)

    def clear(self) -> None:
        self._spans.clear()

    def spans(self) -> List[Span]:
        return list(self._spans)

    # ------------------------------------------------------------- exporting
    def chrome_trace(self) -> Dict[str, object]:
        """Chrome trace-event JSON object: ``{"traceEvents": [...]}`` of
        "X" (complete) events, timestamps in microseconds.  Loadable by
        chrome://tracing and Perfetto, mergeable next to a jax device trace.
        Top-level ``metadata.clock_sync`` anchors this process's monotonic
        span clock to unix time for ``scripts/trace_merge.py``.
        """
        pid = os.getpid()
        events: List[dict] = []
        seen_tids = {}
        for s in self.spans():
            if s.tid not in seen_tids:
                seen_tids[s.tid] = s.thread_name
                events.append(
                    {
                        "ph": "M",
                        "pid": pid,
                        "tid": s.tid,
                        "name": "thread_name",
                        "args": {"name": s.thread_name},
                    }
                )
            ev = {
                "ph": "X" if s.dur_ns is not None else "i",
                "pid": pid,
                "tid": s.tid,
                "name": s.name,
                "ts": s.start_ns / 1000.0,
            }
            if s.dur_ns is not None:
                ev["dur"] = s.dur_ns / 1000.0
            else:
                ev["s"] = "t"
            if s.args:
                ev["args"] = dict(s.args)
            if s.span_id is not None:
                ids = ev.setdefault("args", {})
                ids["trace_id"] = f"{s.trace_id:032x}"
                ids["span_id"] = f"{s.span_id:016x}"
                if s.parent_id is not None:
                    ids["parent_id"] = f"{s.parent_id:016x}"
            events.append(ev)
        unix_ns, perf_ns = self._clock_anchor
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "clock_sync": {
                    "pid": pid,
                    "unix_time_ns": unix_ns,
                    "perf_counter_ns": perf_ns,
                }
            },
        }

    def export_chrome_trace(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path`` (atomic rename)."""
        data = self.chrome_trace()
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        return path


_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def span(name: str, **args) -> _ActiveSpan:
    """``with telemetry.span("act"): ...`` against the default tracer."""
    return get_tracer().span(name, **args)


def root_span(name: str, **args) -> _ActiveSpan:
    """Start a new trace on the default tracer (see :meth:`Tracer.root_span`)."""
    return get_tracer().root_span(name, **args)


def child_span(name: str, parent: Optional[TraceContext], **args) -> _ActiveSpan:
    """Continue a remote trace on the default tracer (see
    :meth:`Tracer.child_span`)."""
    return get_tracer().child_span(name, parent, **args)
