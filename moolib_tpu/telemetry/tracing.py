"""Lightweight host-side span tracer with Chrome trace-event export.

``jax.profiler`` owns the *device* timeline (XLA execution, HBM, ICI); this
tracer owns the *host* side: nested spans around the train loop's act/learn/
reduce phases, RPC rounds, env waits.  Spans export as Chrome trace-event
JSON (``chrome://tracing`` / Perfetto "Complete" events), so a host trace
can sit next to a ``jax.profiler`` capture — and when a jax trace is active
and annotations are enabled, each span also enters a
``jax.profiler.TraceAnnotation`` so the same names appear inside the device
timeline (the merge path :func:`moolib_tpu.utils.profiling.annotate`
documents).

Recording is bounded (a ring of the newest ``capacity`` spans) and cheap:
one ``perf_counter_ns`` pair plus a deque append per span; nesting depth is
tracked per-thread with no locks on the hot path.  Stdlib only unless
annotations are switched on.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "Tracer", "get_tracer", "span"]


class Span:
    """One closed span: name, start (ns since epoch-ish origin), duration."""

    __slots__ = ("name", "start_ns", "dur_ns", "tid", "thread_name", "args")

    def __init__(self, name, start_ns, dur_ns, tid, thread_name, args):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.thread_name = thread_name
        self.args = args


class _ActiveSpan:
    __slots__ = ("_tracer", "_name", "_args", "_t0", "_annotation")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._annotation = None

    def __enter__(self):
        if self._tracer._annotate:
            ann = _jax_annotation(self._name)
            if ann is not None:
                ann.__enter__()
                self._annotation = ann
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        if self._annotation is not None:
            self._annotation.__exit__(*exc)
        t = threading.current_thread()
        self._tracer._spans.append(
            Span(self._name, self._t0, dur, t.ident or 0, t.name, self._args)
        )
        return False


def _jax_annotation(name: str):
    """A jax TraceAnnotation when jax is already imported; never imports it
    (the tracer must stay usable in env workers that never touch jax)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — annotation is best-effort decoration
        return None


class Tracer:
    """Bounded span recorder.  ``get_tracer()`` returns the process default."""

    def __init__(self, capacity: int = 65536):
        self._spans: deque = deque(maxlen=capacity)
        self._annotate = False

    def span(self, name: str, **args) -> _ActiveSpan:
        """Context manager recording one span; nest freely (the Chrome view
        reconstructs nesting from same-thread containment)."""
        return _ActiveSpan(self, name, args or None)

    def enable_jax_annotations(self, enabled: bool = True) -> None:
        """Mirror every span into ``jax.profiler.TraceAnnotation`` so host
        phases appear inside device traces.  Off by default: creating an
        annotation per span costs even when no device trace is running."""
        self._annotate = bool(enabled)

    def clear(self) -> None:
        self._spans.clear()

    def spans(self) -> List[Span]:
        return list(self._spans)

    # ------------------------------------------------------------- exporting
    def chrome_trace(self) -> Dict[str, object]:
        """Chrome trace-event JSON object: ``{"traceEvents": [...]}`` of
        "X" (complete) events, timestamps in microseconds.  Loadable by
        chrome://tracing and Perfetto, mergeable next to a jax device trace.
        """
        pid = os.getpid()
        events: List[dict] = []
        seen_tids = {}
        for s in self.spans():
            if s.tid not in seen_tids:
                seen_tids[s.tid] = s.thread_name
                events.append(
                    {
                        "ph": "M",
                        "pid": pid,
                        "tid": s.tid,
                        "name": "thread_name",
                        "args": {"name": s.thread_name},
                    }
                )
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": s.tid,
                "name": s.name,
                "ts": s.start_ns / 1000.0,
                "dur": s.dur_ns / 1000.0,
            }
            if s.args:
                ev["args"] = dict(s.args)
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path`` (atomic rename)."""
        data = self.chrome_trace()
        tmp = path + ".tmp"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        return path


_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def span(name: str, **args) -> _ActiveSpan:
    """``with telemetry.span("act"): ...`` against the default tracer."""
    return get_tracer().span(name, **args)
