"""Recovery-phase accounting: ONE histogram family for every kind of
come-back the stack performs (docs/RESILIENCE.md "Recovery budget").

The soak's headline number — seconds from a peer's kill to its first fresh
contribution — is useless for *fixing* slow recovery unless it decomposes
into phases with separate owners and knobs.  Every recovery path in the
stack therefore observes into the same labeled histogram::

    recovery_seconds{phase=...}

Phases (the peer-rejoin chain tiles the restart timeline end to end):

- ``reconnect``          — process start (Accumulator construction) to the
  first membership epoch that includes this peer (broker dial + first push).
- ``re_elect``           — membership epoch change to the election result
  (observed every epoch: elections are a per-churn cost, not just restart).
- ``model_sync``         — election result to ``epoch_synced`` on a
  non-leader (chunked model transfer, or the warm-rejoin fast path).
- ``first_compile``      — first sync to the train loop's first gradient
  contribution call (dominated by XLA compile of the grad step; the
  persistent compile cache exists to shrink exactly this bar).
- ``first_contribution`` — that first contribution call to the first
  applied cohort gradient result (the peer is productive again).
- ``worker_respawn``     — EnvPool supervisor: worker death detected to the
  respawned slot re-attached with its unfinished steps re-issued
  (:meth:`moolib_tpu.envpool.EnvPool._supervise_dead_worker`).
- ``broker_failover``    — a peer's broker pings going silent (or answered
  by a demoted standby) to the first successful ping against the NEW
  primary after the failover scan picked it
  (:meth:`moolib_tpu.group.Group.set_brokers`).

Buckets span 50 ms (same-host respawn) to 5 min (cold jax start on a
loaded box) — wider than the default latency buckets because recovery is a
seconds-scale phenomenon by design.
"""

from __future__ import annotations

from .metrics import Histogram, get_registry

__all__ = ["RECOVERY_BUCKETS", "RECOVERY_PHASES", "observe_phase", "recovery_histogram"]

RECOVERY_PHASES = (
    "reconnect",
    "re_elect",
    "model_sync",
    "first_compile",
    "first_contribution",
    "worker_respawn",
    "broker_failover",
)

RECOVERY_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 40.0, 60.0, 120.0, 300.0,
)


def recovery_histogram() -> Histogram:
    """The process-wide ``recovery_seconds`` family (idempotent)."""
    return get_registry().histogram(
        "recovery_seconds",
        "seconds spent per recovery phase (peer rejoin, worker respawn)",
        ("phase",),
        buckets=RECOVERY_BUCKETS,
    )


def observe_phase(phase: str, seconds: float) -> None:
    """Record one phase duration.  ``phase`` should come from
    :data:`RECOVERY_PHASES` (new phases are allowed but must be documented
    in docs/TELEMETRY.md)."""
    recovery_histogram().observe(float(seconds), phase=phase)
