"""Catch: a minimal learnable pixel environment (deepmind bsuite-style task).

A ball falls from the top of a rows×cols board; the agent moves a paddle on
the bottom row (actions: left/stay/right) and gets +1 for catching, -1 for
missing.  Serves as the "Atari" stand-in for IMPALA integration tests: pixel
observations, episodic reward, and solvable quickly from pixels — the role
ALE/Pong plays for the reference (``examples/vtrace/config.yaml:23-65``),
without the ALE dependency.
"""

from __future__ import annotations

import numpy as np


class CatchEnv:
    num_actions = 3

    def __init__(self, rows: int = 10, columns: int = 5, seed=None, frame_shape=None):
        self.rows = rows
        self.columns = columns
        self._rng = np.random.default_rng(seed)
        self._ball = [0, 0]
        self._paddle = 0
        # Optional upscaled frame (e.g. (84, 84)) to exercise conv encoders.
        self._frame_shape = frame_shape

    @property
    def observation_shape(self):
        if self._frame_shape is not None:
            return (*self._frame_shape, 1)
        return (self.rows, self.columns, 1)

    @property
    def obs_spec(self):
        """``(shape, dtype)`` — the construction surface shared with the
        pure-JAX env family (``envs.jax_envs.JaxEnv``), so benches and the
        experiment size either backend through one factory."""
        return self.observation_shape, np.dtype(np.uint8)

    def _sample_column(self) -> int:
        """Per-episode drop column — THE env's only entropy.  Overridable so
        a shared-seed harness (``jax_envs.host_catch``) can pin the stream
        to the on-device derivation for bit-exactness proofs."""
        return int(self._rng.integers(self.columns))

    def _obs(self):
        board = np.zeros((self.rows, self.columns, 1), dtype=np.uint8)
        board[self._ball[0], self._ball[1], 0] = 255
        board[self.rows - 1, self._paddle, 0] = 255
        if self._frame_shape is not None:
            h, w = self._frame_shape
            ry, rx = h // self.rows, w // self.columns
            big = np.zeros((h, w, 1), dtype=np.uint8)
            up = np.kron(board[..., 0], np.ones((ry, rx), dtype=np.uint8))
            big[: up.shape[0], : up.shape[1], 0] = up
            return big
        return board

    def reset(self):
        self._ball = [0, self._sample_column()]
        self._paddle = self.columns // 2
        return self._obs()

    def step(self, action):
        action = int(np.asarray(action).reshape(()))
        self._paddle = int(np.clip(self._paddle + (action - 1), 0, self.columns - 1))
        self._ball[0] += 1
        done = self._ball[0] == self.rows - 1
        reward = 0.0
        if done:
            reward = 1.0 if self._ball[1] == self._paddle else -1.0
        return self._obs(), reward, done, {}


class FlatCatchEnv(CatchEnv):
    """Catch with the board flattened to a 1-D uint8 vector.

    Routes through the MLP (``ActorCriticNet``) instead of the conv encoder:
    the per-frame model compute drops to microseconds, which makes this the
    actor-data-plane benchmark env — at this scale whole-agent SPS measures
    dispatch/copy overhead per frame, not conv FLOPs, the same regime a TPU
    learner leaves the actor loop in (``benchmarks/agent_bench.py --scale
    small``).  Observations stay uint8 so the single-crossing upload
    contract is exercised end to end.
    """

    @property
    def observation_shape(self):
        h, w, c = super().observation_shape
        return (h * w * c,)

    @property
    def obs_spec(self):
        return self.observation_shape, np.dtype(np.uint8)

    def _obs(self):
        return super()._obs().reshape(-1)


class FrameStack:
    """Stack the last ``num_stack`` single-channel frames on the channel axis
    (the reference trains on (84, 84, 4) stacked Atari frames,
    ``examples/atari/environment.py``; AtariPreprocessing stacks internally —
    this is the generic wrapper for envs that emit one frame per step)."""

    def __init__(self, env, num_stack: int = 4):
        self.env = env
        self.num_stack = num_stack
        self._frames = None
        self.num_actions = env.num_actions

    @property
    def observation_shape(self):
        h, w, c = self.env.observation_shape
        return (h, w, c * self.num_stack)

    @property
    def obs_spec(self):
        _, dtype = self.env.obs_spec
        return self.observation_shape, dtype

    def _obs(self):
        return np.concatenate(self._frames, axis=-1)

    def reset(self):
        first = self.env.reset()
        self._frames = [first] * self.num_stack
        return self._obs()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        self._frames = self._frames[1:] + [obs]
        return self._obs(), reward, done, info
