"""Self-contained environments with the gym step/reset protocol.

The reference depends on external ``gym``/ALE for its examples and tests
(``examples/atari/environment.py:19-40``); this image has neither, so the
framework ships its own envs: CartPole (classic control, used by the A2C
example like the reference's CartPole-v1), Catch (a minimal *learnable*
pixel game standing in for Atari in IMPALA integration tests), and a
synthetic Atari-shaped env for throughput benchmarking.
"""

from .cartpole import CartPoleEnv  # noqa: F401
from .catch import CatchEnv  # noqa: F401
from .synthetic import SyntheticAtariEnv  # noqa: F401
