"""Self-contained environments with the gym step/reset protocol.

The reference depends on external ``gym``/ALE for its examples and tests
(``examples/atari/environment.py:19-40``).  This package ships self-contained
envs — CartPole (classic control, used by the A2C example like the
reference's CartPole-v1), Catch (a minimal *learnable* pixel game standing
in for Atari in IMPALA integration tests), and a synthetic Atari-shaped env
for throughput benchmarking — plus ``atari.py``: the reference's full Atari
preprocessing stack (frameskip/max-pool, grayscale, 84x84, sticky actions,
frame stack) over any gymnasium-API env, a :class:`GymEnv` protocol adapter
for gymnasium ids, and an ALE factory (``create_env``) that needs ale_py
(not in this image; the preprocessing itself is tested without it).
"""

from .atari import AtariPreprocessing, GymEnv, create_env  # noqa: F401
from .cartpole import CartPoleEnv  # noqa: F401
from .catch import CatchEnv, FlatCatchEnv, FrameStack  # noqa: F401
from .jax_envs import (  # noqa: F401
    JaxCatch,
    JaxEnv,
    JaxProcCatch,
    make_jax_env,
)
from .synthetic import SyntheticAtariEnv  # noqa: F401
