"""Atari environment factory with reference-parity preprocessing.

Mirrors the reference's ALE setup (``examples/atari/environment.py:19-40``
and ``examples/atari/atari_preprocessing.py``): grayscale, frame-skip with
max-pooling over the last two raw frames, 84x84 area resize, sticky
actions, and a 4-frame stack — producing the (84, 84, 4) uint8 observations
the IMPALA agent trains on.

The preprocessing is implemented here against the plain gymnasium API (so
it is unit-testable without ROMs); only :func:`create_env` needs ``ale_py``,
and raises a clear error when it is absent (this image ships gymnasium but
no ALE).  :class:`GymEnv` adapts any gymnasium env to the framework's
``reset() -> obs`` / ``step(a) -> (obs, reward, done, info)`` protocol used
by :class:`moolib_tpu.EnvPool`.
"""

from __future__ import annotations

from collections import deque

import numpy as np


class GymEnv:
    """Adapter: gymnasium's (obs, info) / 5-tuple API -> the framework's
    old-gym protocol (``reset() -> obs``, ``step(a) -> (obs, r, done, info)``,
    ``done = terminated or truncated``)."""

    def __init__(self, env_or_id, seed=None, **make_kwargs):
        import gymnasium

        if isinstance(env_or_id, str):
            env_or_id = gymnasium.make(env_or_id, **make_kwargs)
        self.env = env_or_id
        self._seed = seed
        space = getattr(self.env, "action_space", None)
        # Strict isinstance: MultiBinary etc. also duck-type ``.n``.
        if not isinstance(space, gymnasium.spaces.Discrete):
            raise ValueError(
                f"{self.env} has action space {space!r}; the framework agents "
                "act by integer index, so only Discrete action spaces are "
                "supported"
            )
        self.num_actions = int(space.n)

    @property
    def obs_spec(self):
        """Shared construction surface (``envs.jax_envs.JaxEnv``): gymnasium
        envs declare shape/dtype on their observation space."""
        space = self.env.observation_space
        return tuple(space.shape), np.dtype(space.dtype)

    def reset(self):
        obs, _ = self.env.reset(seed=self._seed)
        self._seed = None  # reseed only on the first reset
        return np.asarray(obs)

    def step(self, action):
        action = np.asarray(action).reshape(()).item()
        obs, reward, terminated, truncated, info = self.env.step(action)
        return np.asarray(obs), float(reward), bool(terminated or truncated), info

    def close(self):
        if hasattr(self.env, "close"):
            self.env.close()


class AtariPreprocessing:
    """Standard Atari preprocessing (Machado et al. 2018), as the reference
    applies it: wraps a *raw* gymnasium-API env emitting RGB frames and
    exposes the framework protocol with (screen_size, screen_size, num_stack)
    uint8 observations.

    - ``frame_skip`` emulator steps per agent step, rewards summed; the
      emitted frame is the pixelwise max of the last two raw frames
      (flicker removal).
    - luminance grayscale + ``screen_size``² area resize.
    - sticky actions: at every *emulator* frame, with probability
      ``sticky_action_prob`` the previously-executed action repeats
      (Machado et al. §5; apply EITHER here or in ALE itself, not both —
      the reference uses the v5 env's built-in 0.25).
    - ``terminal_on_life_loss``: losing a life ends the *agent* episode, but
      the next ``reset()`` continues the same game with a no-op step; only
      real game-over restarts the emulator (standard episodic-life wrapper).
    - ``num_stack`` processed frames stacked on the channel axis.
    """

    def __init__(
        self,
        env,
        frame_skip: int = 4,
        screen_size: int = 84,
        sticky_action_prob: float = 0.0,
        num_stack: int = 4,
        terminal_on_life_loss: bool = False,
        noop_max: int = 0,
        seed=None,
    ):
        if frame_skip < 1:
            raise ValueError("frame_skip must be >= 1")
        self.env = env
        self.frame_skip = frame_skip
        self.screen_size = screen_size
        self.sticky_action_prob = sticky_action_prob
        self.num_stack = num_stack
        self.terminal_on_life_loss = terminal_on_life_loss
        self.noop_max = noop_max
        self._rng = np.random.default_rng(seed)
        self._seed = seed
        self._stack = deque(maxlen=num_stack)
        self._prev_action = 0
        self._lives = None
        self._needs_full_reset = True
        self.num_actions = int(env.action_space.n)

    @property
    def observation_shape(self):
        return (self.screen_size, self.screen_size, self.num_stack)

    @property
    def obs_spec(self):
        return self.observation_shape, np.dtype(np.uint8)

    def _to_gray(self, frame):
        frame = np.asarray(frame)
        if frame.ndim == 3 and frame.shape[-1] == 3:
            try:
                import cv2

                return cv2.cvtColor(frame, cv2.COLOR_RGB2GRAY)
            except ImportError:
                # ITU-R 601 luminance, ROUNDED (truncation would map (v,v,v)
                # to v-1 where the float sum lands just under v); cv2's
                # fixed-point rounding can still differ by 1 LSB on general
                # RGB, so prefer cv2 when present.
                frame = np.round(
                    frame @ np.array([0.299, 0.587, 0.114])
                ).astype(np.uint8)
        return frame

    def _process(self, frame, prev_frame=None):
        # Grayscale each raw frame FIRST, then max-pool: pixelwise
        # luminance(max(rgb)) != max(luminance), and the reference pools
        # already-grayscale screen buffers.
        frame = self._to_gray(frame)
        if prev_frame is not None:
            frame = np.maximum(frame, self._to_gray(prev_frame))
        if frame.shape[:2] != (self.screen_size, self.screen_size):
            import cv2

            frame = cv2.resize(
                frame,
                (self.screen_size, self.screen_size),
                interpolation=cv2.INTER_AREA,
            )
        return np.asarray(frame, dtype=np.uint8)

    def _obs(self):
        return np.stack(self._stack, axis=-1)

    def reset(self):
        if self._needs_full_reset:
            obs, _ = self.env.reset(seed=self._seed)
            self._seed = None
            # Random no-op starts (1..noop_max emulator no-ops on a full
            # game reset), the reference's evaluation convention for
            # de-determinizing start states.
            if self.noop_max:
                for _ in range(int(self._rng.integers(1, self.noop_max + 1))):
                    obs, _, terminated, truncated, _ = self.env.step(0)
                    if terminated or truncated:
                        obs, _ = self.env.reset()
        else:
            # Life lost but the game is still on: continue it with a no-op
            # so the agent sees post-first-life states (episodic-life).
            obs, _, terminated, truncated, _ = self.env.step(0)
            if terminated or truncated:
                obs, _ = self.env.reset()
        self._needs_full_reset = False
        self._prev_action = 0
        self._lives = self._get_lives()
        first = self._process(np.asarray(obs))
        self._stack.clear()
        for _ in range(self.num_stack):
            self._stack.append(first)
        return self._obs()

    def _get_lives(self):
        ale = getattr(getattr(self.env, "unwrapped", self.env), "ale", None)
        return ale.lives() if ale is not None else None

    def step(self, action):
        action = int(np.asarray(action).reshape(()))
        total_reward = 0.0
        done = False
        info = {}
        frame = prev_frame = None
        for t in range(self.frame_skip):
            # Sticky coin is drawn per emulator frame: the executed action
            # can flip mid-skip (Machado et al. §5).
            exec_action = action
            if self.sticky_action_prob and self._rng.random() < self.sticky_action_prob:
                exec_action = self._prev_action
            self._prev_action = exec_action
            obs, reward, terminated, truncated, info = self.env.step(exec_action)
            total_reward += float(reward)
            # Keep the last two raw frames for flicker max-pooling.
            if t >= self.frame_skip - 2:
                prev_frame, frame = frame, np.asarray(obs)
            done = bool(terminated or truncated)
            if done:
                self._needs_full_reset = True
            elif self.terminal_on_life_loss:
                lives = self._get_lives()
                if lives is not None and self._lives is not None and lives < self._lives:
                    done = True  # agent episode ends; game continues on reset
                self._lives = lives
            if done:
                frame, prev_frame = np.asarray(obs), prev_frame
                break
        self._stack.append(self._process(frame, prev_frame))
        return self._obs(), total_reward, done, info

    def close(self):
        if hasattr(self.env, "close"):
            self.env.close()


def create_env(
    game: str = "Pong",
    *,
    frame_skip: int = 4,
    screen_size: int = 84,
    num_stack: int = 4,
    sticky_actions: bool = True,
    full_action_space: bool = True,
    noop_max: int = 30,
    seed=None,
):
    """ALE factory matching the reference (``examples/atari/environment.py``):
    ``ALE/<game>-v5`` with emulator-level frameskip/sticky disabled so the
    wrapper (testable, explicit) owns them.  Defaults follow the reference's
    evaluation convention: the full 18-action space and random no-op starts
    (``noop_max=30``).  Needs ``ale_py`` + ROMs."""
    try:
        import gymnasium

        raw = gymnasium.make(
            f"ALE/{game}-v5",
            frameskip=1,
            repeat_action_probability=0.0,
            full_action_space=full_action_space,
        )
    except Exception as e:
        # Only blame a missing ale_py when it actually is missing; anything
        # else (e.g. a typo'd game name with ale_py installed) keeps its own
        # message — gymnasium's NameNotFound includes a did-you-mean.
        import importlib.util

        if importlib.util.find_spec("ale_py") is not None:
            raise
        raise ImportError(
            f"creating ALE/{game}-v5 failed ({e!r}). Real Atari needs the "
            "ale_py package and its ROMs (pip install ale-py gymnasium[atari]); "
            "this environment ships neither — use the built-in 'catch'/"
            "'pixel_catch' pixel envs or envs.SyntheticAtariEnv instead."
        ) from e
    return AtariPreprocessing(
        raw,
        frame_skip=frame_skip,
        screen_size=screen_size,
        sticky_action_prob=0.25 if sticky_actions else 0.0,
        num_stack=num_stack,
        noop_max=noop_max,
        seed=seed,
    )
