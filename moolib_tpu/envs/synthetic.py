"""Synthetic Atari-shaped environment for throughput benchmarking.

Produces frame-stacked uint8 observations with the reference IMPALA input
shape ([84, 84, 4] grayscale frame stack, ``examples/atari/environment.py``)
at near-zero CPU cost, so EnvPool/actor-loop benchmarks measure the framework
rather than an emulator.
"""

from __future__ import annotations

import numpy as np


class SyntheticAtariEnv:
    num_actions = 6

    def __init__(self, height: int = 84, width: int = 84, frames: int = 4, seed=None,
                 episode_length: int = 1000):
        self.observation_shape = (height, width, frames)
        # Shared construction surface (envs.jax_envs.JaxEnv): (shape, dtype).
        self.obs_spec = (self.observation_shape, np.dtype(np.uint8))
        self._rng = np.random.default_rng(seed)
        self._episode_length = episode_length
        self._t = 0
        # A small bank of pre-generated frames; stepping just rotates them.
        self._bank = self._rng.integers(
            0, 256, size=(8, height, width, frames), dtype=np.uint8
        )

    def reset(self):
        self._t = 0
        return self._bank[0]

    def step(self, action):
        self._t += 1
        obs = self._bank[self._t % len(self._bank)]
        reward = float(self._rng.random() < 0.05)
        done = self._t >= self._episode_length
        return obs, reward, done, {}
