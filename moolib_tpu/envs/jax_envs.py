"""Pure-JAX on-device environments (the Podracer "Anakin" env family).

The device-resident actor pipeline (PR 5, ``moolib_tpu/rollout.py``) cut the
actor data plane to one host-boundary crossing per frame — but the crossing
is still there, because the env steps on the host (and, for EnvPool, in
another process).  The Podracer paper (arXiv:2104.06272 § Anakin) closes it
entirely: when ``env.step`` is itself a jittable JAX function, it fuses INTO
the rollout body — observation, action, and reward never exist on the host
at all, and a full ``[T+1, B]`` unroll is produced by one ``lax.scan``
dispatch (:class:`moolib_tpu.rollout.AnakinRollout`).  JaxARC
(arXiv:2601.17564) shows the same pattern for procedurally-generated
puzzle suites.

Protocol (:class:`JaxEnv`) — all methods are pure functions of explicit
state, safe under ``jit``/``vmap``/``scan``:

- ``init(key) -> state``: per-env state pytree for one env (vmap over a
  batch of keys for a batch of envs).  The state embeds the PRNG key and an
  episode counter, so the whole env family is **counter-based**: episode
  ``e`` of the env seeded with ``key`` derives its procedural content from
  ``fold_in(key, e)``, independent of how the episodes are reached
  (per-step loop, scan unroll, or a host reimplementation).
- ``observe(state) -> obs``: the observation for the current state (uint8
  frames stay uint8 — the same native-dtype contract as the host plane).
- ``step(state, action) -> (state, timestep)``: one env step with
  **auto-reset on device**: when the episode ends, the returned timestep
  carries the terminal reward, ``done=True``, and the *reset* observation
  of the next episode — exactly the semantics ``EnvPool``'s worker loop
  gives host envs (``envpool.py _step_env``), so trajectories line up
  across backends.
- ``obs_spec -> (shape, dtype)`` and ``num_actions``: the construction
  surface shared with the host envs (``CatchEnv.obs_spec`` etc.), so
  ``examples/vtrace/experiment.py --env_backend={envpool,jax}`` builds
  either backend through one factory.

The timestep is a dict ``{"state", "reward", "done"}`` with the same keys
as an EnvPool observation batch, so rollout buffers are interchangeable.

Shared seeding contract: :class:`JaxCatch` is a port of
:class:`~moolib_tpu.envs.catch.FlatCatchEnv` whose only entropy is the
ball's drop column, drawn per episode as
``randint(fold_in(key, episode), 0, columns)``.  :func:`host_catch` builds
a host ``FlatCatchEnv`` whose column stream follows the *same* derivation,
so ``tests/test_jax_envs.py`` can assert the two backends produce
bit-identical trajectories — obs, reward, done, across auto-reset
boundaries — under a shared key.
"""

from __future__ import annotations

from typing import Any, Dict, Protocol, Tuple, runtime_checkable

import jax
import jax.numpy as jnp
import numpy as np

TimeStep = Dict[str, jax.Array]  # {"state": obs, "reward": f32, "done": bool}


@runtime_checkable
class JaxEnv(Protocol):
    """Structural protocol for on-device envs (see module docstring)."""

    num_actions: int

    @property
    def obs_spec(self) -> Tuple[Tuple[int, ...], Any]:
        ...

    def init(self, key) -> Dict[str, jax.Array]:
        ...

    def observe(self, state) -> jax.Array:
        ...

    def step(self, state, action) -> Tuple[Dict[str, jax.Array], TimeStep]:
        ...


def _episode_key(key, episode):
    """THE shared seeding contract: everything procedural about episode
    ``e`` of an env seeded with ``key`` derives from this fold — the host
    shim (:func:`host_catch`) and any future backend must use the same
    derivation to stay trajectory-comparable."""
    return jax.random.fold_in(key, episode)


class JaxCatch:
    """Catch with the board flattened to a 1-D uint8 vector, on device.

    Pure-JAX port of :class:`~moolib_tpu.envs.catch.FlatCatchEnv`: a ball
    falls from the top of a rows×columns board, the paddle on the bottom row
    moves left/stay/right, +1 for catching, -1 for missing.  Flattened uint8
    observations route through the ``ActorCriticNet`` MLP — the actor-plane
    benchmark geometry (``agent_bench --scale small``), now with zero
    host-boundary bytes per frame.
    """

    num_actions = 3

    def __init__(self, rows: int = 10, columns: int = 5):
        self.rows = rows
        self.columns = columns

    @property
    def obs_spec(self) -> Tuple[Tuple[int, ...], Any]:
        return ((self.rows * self.columns,), jnp.uint8)

    # ------------------------------------------------------------- episode
    def _episode_fields(self, key, episode):
        """Procedural content of episode ``episode`` (the seeding contract):
        Catch's only entropy is the drop column."""
        col = jax.random.randint(
            _episode_key(key, episode), (), 0, self.columns, dtype=jnp.int32
        )
        return {
            "ball_row": jnp.zeros((), jnp.int32),
            "ball_col": col,
            "paddle": jnp.full((), self.columns // 2, jnp.int32),
        }

    def init(self, key) -> Dict[str, jax.Array]:
        episode = jnp.zeros((), jnp.int32)
        return {"key": key, "episode": episode, **self._episode_fields(key, episode)}

    def observe(self, state) -> jax.Array:
        # Ball pixel then paddle pixel, same write order as the host env
        # (identical even when they overlap on the bottom row: both 255).
        board = jnp.zeros((self.rows, self.columns), jnp.uint8)
        board = board.at[state["ball_row"], state["ball_col"]].set(255)
        board = board.at[self.rows - 1, state["paddle"]].set(255)
        return board.reshape(-1)

    def step(self, state, action) -> Tuple[Dict[str, jax.Array], TimeStep]:
        action = jnp.asarray(action, jnp.int32)
        paddle = jnp.clip(state["paddle"] + (action - 1), 0, self.columns - 1)
        ball_row = state["ball_row"] + 1
        done = ball_row == self.rows - 1
        reward = jnp.where(
            done,
            jnp.where(state["ball_col"] == paddle, 1.0, -1.0),
            0.0,
        ).astype(jnp.float32)
        # Auto-reset on device: the post-done state is the NEXT episode
        # (counter-based procedural fields), and the returned observation is
        # its reset frame — EnvPool's exact worker-loop semantics.
        next_episode = state["episode"] + done.astype(jnp.int32)
        fresh = self._episode_fields(state["key"], next_episode)
        moved = {"ball_row": ball_row, "ball_col": state["ball_col"], "paddle": paddle}
        new_state = {
            "key": state["key"],
            "episode": next_episode,
            **{
                k: jnp.where(done, fresh[k], moved[k])
                for k in ("ball_row", "ball_col", "paddle")
            },
        }
        ts: TimeStep = {
            "state": self.observe(new_state),
            "reward": reward,
            "done": done,
        }
        return new_state, ts


class JaxProcCatch(JaxCatch):
    """Procedurally-generated Catch variant for scenario diversity.

    Every episode draws, from the same counter-based contract, a fresh
    *scenario*: the drop column, a horizontal ball drift in
    ``[-max_drift, max_drift]`` applied every step (the ball bounces off the
    walls), and a distractor pixel column that carries no reward signal.
    The optimal policy must track a moving ball and ignore the distractor —
    a strictly harder family than :class:`JaxCatch` on the same observation
    and action spec, generated entirely on device (the JaxARC pattern:
    procedural scenario parameters live in the state pytree, shapes stay
    static under jit).
    """

    def __init__(self, rows: int = 10, columns: int = 5, max_drift: int = 1,
                 distractor: bool = True):
        super().__init__(rows, columns)
        self.max_drift = max_drift
        self.distractor = distractor

    def _episode_fields(self, key, episode):
        ek = _episode_key(key, episode)
        k_col, k_drift, k_dis = jax.random.split(ek, 3)
        fields = {
            "ball_row": jnp.zeros((), jnp.int32),
            "ball_col": jax.random.randint(k_col, (), 0, self.columns, jnp.int32),
            "paddle": jnp.full((), self.columns // 2, jnp.int32),
            "drift": jax.random.randint(
                k_drift, (), -self.max_drift, self.max_drift + 1, jnp.int32
            ),
            "distractor_col": jax.random.randint(
                k_dis, (), 0, self.columns, jnp.int32
            ),
        }
        return fields

    def observe(self, state) -> jax.Array:
        board = jnp.zeros((self.rows, self.columns), jnp.uint8)
        if self.distractor:
            # Dimmer static column: visible structure, no reward relevance.
            board = board.at[0, state["distractor_col"]].set(128)
        board = board.at[state["ball_row"], state["ball_col"]].set(255)
        board = board.at[self.rows - 1, state["paddle"]].set(255)
        return board.reshape(-1)

    def step(self, state, action) -> Tuple[Dict[str, jax.Array], TimeStep]:
        action = jnp.asarray(action, jnp.int32)
        paddle = jnp.clip(state["paddle"] + (action - 1), 0, self.columns - 1)
        ball_row = state["ball_row"] + 1
        # Drift with wall bounce: reflect the out-of-range column back in.
        raw = state["ball_col"] + state["drift"]
        bounced = jnp.where(
            raw < 0, -raw, jnp.where(raw >= self.columns, 2 * (self.columns - 1) - raw, raw)
        )
        ball_col = jnp.clip(bounced, 0, self.columns - 1)
        done = ball_row == self.rows - 1
        reward = jnp.where(
            done, jnp.where(ball_col == paddle, 1.0, -1.0), 0.0
        ).astype(jnp.float32)
        next_episode = state["episode"] + done.astype(jnp.int32)
        fresh = self._episode_fields(state["key"], next_episode)
        moved = {
            "ball_row": ball_row,
            "ball_col": ball_col,
            "paddle": paddle,
            "drift": state["drift"],
            "distractor_col": state["distractor_col"],
        }
        new_state = {
            "key": state["key"],
            "episode": next_episode,
            **{k: jnp.where(done, fresh[k], moved[k]) for k in fresh},
        }
        ts: TimeStep = {
            "state": self.observe(new_state),
            "reward": reward,
            "done": done,
        }
        return new_state, ts


# --------------------------------------------------------------------------
# Batch helpers (vmap over per-env keys)
# --------------------------------------------------------------------------


def batch_init(env: JaxEnv, key, batch_size: int):
    """State pytree for ``batch_size`` envs: env ``i`` is seeded with
    ``fold_in(key, i)`` — the per-env half of the seeding contract."""
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(batch_size))
    return jax.vmap(env.init)(keys)


def batch_observe(env: JaxEnv, state):
    return jax.vmap(env.observe)(state)


def batch_step(env: JaxEnv, state, action):
    return jax.vmap(env.step)(state, action)


# --------------------------------------------------------------------------
# Host-side shim: the other half of the bit-exactness proof
# --------------------------------------------------------------------------


def host_catch(key, rows: int = 10, columns: int = 5):
    """A host :class:`~moolib_tpu.envs.catch.FlatCatchEnv` whose per-episode
    ball columns follow the SAME counter-based derivation as
    :class:`JaxCatch` seeded with ``key`` — the host half of the shared
    seeding contract.  Used by ``tests/test_jax_envs.py`` to prove the
    on-device port bit-exact against the host env it replaces (column
    values are computed eagerly with the same jax.random calls)."""
    from .catch import FlatCatchEnv

    class _SharedSeedCatch(FlatCatchEnv):
        def __init__(self):
            super().__init__(rows=rows, columns=columns)
            self._episode = 0

        def _sample_column(self) -> int:
            col = int(
                jax.random.randint(
                    _episode_key(key, self._episode), (), 0, self.columns,
                    dtype=jnp.int32,
                )
            )
            self._episode += 1
            return col

    return _SharedSeedCatch()


def make_jax_env(name: str, **kwargs) -> JaxEnv:
    """Factory behind ``--env_backend jax``: map the experiment's ``--env``
    names onto the on-device family.  ``catch_flat`` is the same geometry as
    the host env of that name; ``catch_proc`` is the procedurally-generated
    variant (same spec, harder scenario family)."""
    if name in ("catch_flat", "jax_catch", "catch"):
        return JaxCatch(**kwargs)
    if name in ("catch_proc", "proc_catch", "jax_proc"):
        return JaxProcCatch(**kwargs)
    raise ValueError(
        f"no jax env for --env {name!r} (catch_flat | catch_proc; the other "
        "env names are host/EnvPool-backed — drop --env_backend jax)"
    )


__all__ = [
    "JaxEnv",
    "JaxCatch",
    "JaxProcCatch",
    "TimeStep",
    "batch_init",
    "batch_observe",
    "batch_step",
    "host_catch",
    "make_jax_env",
]
