"""CartPole with the classic Barto-Sutton-Anderson dynamics (gym API).

Same task the reference's A2C example trains on (``examples/a2c.py``,
CartPole-v1: 2 actions, 4-dim state, reward 1 per step, 500-step limit).
"""

from __future__ import annotations

import numpy as np


class CartPoleEnv:
    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSPOLE + MASSCART
    LENGTH = 0.5  # half the pole's length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * np.pi / 360
    X_THRESHOLD = 2.4

    num_actions = 2
    observation_shape = (4,)

    @property
    def obs_spec(self):
        """``(shape, dtype)`` — the shared construction surface (see
        ``envs.jax_envs.JaxEnv``)."""
        return self.observation_shape, np.dtype(np.float32)

    def __init__(self, seed: int | None = None, max_episode_steps: int = 500):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, dtype=np.float32)
        self._steps = 0
        self._max_episode_steps = max_episode_steps

    def reset(self):
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self._steps = 0
        return self._state.copy()

    def step(self, action):
        action = int(np.asarray(action).reshape(()))
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        costheta = np.cos(theta)
        sintheta = np.sin(theta)
        temp = (force + self.POLEMASS_LENGTH * theta_dot**2 * sintheta) / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / self.TOTAL_MASS)
        )
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta / self.TOTAL_MASS
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot], dtype=np.float32)
        self._steps += 1
        terminated = bool(
            x < -self.X_THRESHOLD
            or x > self.X_THRESHOLD
            or theta < -self.THETA_THRESHOLD
            or theta > self.THETA_THRESHOLD
        )
        truncated = self._steps >= self._max_episode_steps
        return self._state.copy(), 1.0, terminated or truncated, {}
