"""Checkpoint/resume: durable training state with atomic installs.

The reference leaves checkpoint I/O to the application (``torch.save`` to
tmp+rename, leader-only, ``examples/vtrace/experiment.py:186-204,439-468``)
and provides the cohort-sync hooks (``Accumulator.set_state/state``,
``set_model_version``).  Here the framework owns the I/O too:

- :class:`Checkpointer` — orbax-backed when available (async-capable,
  sharding-aware: restores resharded arrays directly onto a mesh), with a
  pickle fallback; atomic installs either way; retains the last N.
- Integrity is first-class (docs/RESILIENCE.md): every ``step_<N>/``
  carries a ``manifest.json`` (step, file list, sizes, sha256) written
  before the atomic rename, so a checkpoint is either whole or
  detectably partial.  ``restore()`` validates the manifest and, on
  corruption/truncation, *falls back to the newest intact older
  checkpoint* instead of raising — logging what it skipped and bumping
  the ``checkpoint_corrupt_skipped`` telemetry counter.  ``all_steps()``
  ignores manifest-less partial directories for the same reason.
- The cohort-sync side stays on the Accumulator exactly like the reference:
  restore → ``accumulator.set_model_version(step)`` so leader election
  prefers the restored peer.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

import jax

from . import telemetry, utils

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except ImportError:  # pragma: no cover
    ocp = None
    _HAS_ORBAX = False

_REG = telemetry.get_registry()
_M_CORRUPT_SKIPPED = _REG.counter(
    "checkpoint_corrupt_skipped",
    "corrupt/partial checkpoints skipped by restore() fallback",
)

_MANIFEST = "manifest.json"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class Checkpointer:
    """Save/restore arbitrary pytrees of arrays + metadata under a directory.

    Layout: ``<dir>/step_<N>/`` per checkpoint (each with a
    ``manifest.json`` integrity record) plus a ``latest`` symlink.
    """

    def __init__(self, directory: str, max_to_keep: int = 3, use_orbax: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._use_orbax = _HAS_ORBAX if use_orbax is None else (use_orbax and _HAS_ORBAX)
        self._ckptr = ocp.PyTreeCheckpointer() if self._use_orbax else None
        self._warned_partial: set = set()  # manifest-less dirs already logged

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any) -> str:
        """Write a checkpoint for ``step``; returns its path. Atomic: partial
        writes land in a tmp dir (manifest included) that is renamed into
        place — a crash mid-save can only ever leave a ``.tmp`` husk."""
        path = self._step_path(step)
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        host_state = jax.device_get(state)
        if self._use_orbax:
            self._ckptr.save(tmp, host_state)
        else:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
        self._write_manifest(tmp, step)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        self._update_latest(path)
        self._gc()
        utils.log_info("checkpoint: saved step %d to %s", step, path)
        return path

    def _write_manifest(self, tmp: str, step: int) -> None:
        files: Dict[str, Dict[str, object]] = {}
        for root, _dirs, names in os.walk(tmp):
            for name in names:
                if name == _MANIFEST:
                    continue
                full = os.path.join(root, name)
                rel = os.path.relpath(full, tmp)
                files[rel] = {"size": os.path.getsize(full), "sha256": _sha256(full)}
        manifest = {
            "step": int(step),
            "format": "orbax" if self._use_orbax else "pickle",
            "time": time.time(),
            "files": files,
        }
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)

    # --------------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None, target: Any = None) -> Optional[Any]:
        """Load the newest *intact* checkpoint (≤ ``step`` when given);
        None if none exists.

        A candidate whose manifest is missing, unparsable, or whose files
        fail the size/sha256 check — or whose payload fails to deserialize
        — is logged, counted (``checkpoint_corrupt_skipped``) and skipped
        in favor of the next older one: a torn write or a truncated disk
        must cost one checkpoint interval, not the run.

        With orbax and a ``target`` pytree of sharded arrays, restored
        leaves land directly with the target's shardings (no host round
        trip on the user side).
        """
        candidates = self.all_steps()
        if step is not None:
            candidates = [s for s in candidates if s <= step]
            if not candidates or candidates[-1] != step:
                utils.log_error(
                    "checkpoint: step %s missing or partial under %s",
                    step, self.directory,
                )
        for cand in reversed(candidates):
            path = self._step_path(cand)
            reason = self._verify(path)
            if reason is not None:
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping corrupt %s (%s); falling back", path, reason
                )
                continue
            try:
                return self._load(path, target)
            except Exception as e:  # noqa: BLE001 — treat as corruption
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping unreadable %s (%r); falling back", path, e
                )
        return None

    def _load(self, path: str, target: Any):
        is_pickle = os.path.exists(os.path.join(path, "state.pkl"))
        if not is_pickle:
            if not self._use_orbax:
                raise RuntimeError(
                    f"checkpoint {path} was written by orbax but orbax is "
                    "unavailable here (install orbax-checkpoint or restore "
                    "on the saving host)"
                )
            if target is not None:
                return self._ckptr.restore(path, item=target)
            return self._ckptr.restore(path)
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def _verify(self, path: str) -> Optional[str]:
        """None when ``path`` matches its manifest; else a human reason."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return f"manifest unreadable: {e}"
        files = manifest.get("files")
        if not isinstance(files, dict):
            return "manifest has no file table"
        for rel, meta in files.items():
            full = os.path.join(path, rel)
            if not os.path.exists(full):
                return f"missing file {rel}"
            size = os.path.getsize(full)
            if size != meta.get("size"):
                return f"truncated {rel} ({size} != {meta.get('size')} bytes)"
            if _sha256(full) != meta.get("sha256"):
                return f"checksum mismatch on {rel}"
        return None

    def verify(self, step: int) -> bool:
        """Public integrity probe: does ``step`` exist and match its
        manifest byte-for-byte?"""
        return self._verify(self._step_path(step)) is None

    def all_steps(self) -> List[int]:
        """Steps with a manifest present.  A ``step_<N>/`` without one is a
        partial artifact (pre-rename husk, hand-damaged, or written by a
        pre-manifest version) and is ignored — it must never be selected as
        'latest'.  Skips are logged once per directory so a legacy
        checkpoint dir can't silently read as empty."""
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                if not os.path.exists(os.path.join(self.directory, name, _MANIFEST)):
                    if name not in self._warned_partial:
                        self._warned_partial.add(name)
                        utils.log_error(
                            "checkpoint: ignoring %s/%s (no %s — partial or "
                            "pre-manifest; re-save to adopt it)",
                            self.directory, name, _MANIFEST,
                        )
                    continue
                try:
                    steps.append(int(name[len("step_") :]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_intact_step(self) -> Optional[int]:
        """The newest step whose manifest verifies byte-for-byte — the step
        ``restore()`` would actually load.  Warm-rejoin callers use this to
        learn the version a restart will advertise (and chaos harnesses to
        predict the resume point after a truncation) WITHOUT paying the
        payload deserialization."""
        for step in reversed(self.all_steps()):
            if self._verify(self._step_path(step)) is None:
                return step
        return None

    # ------------------------------------------------------------- internals
    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def _update_latest(self, path: str) -> None:
        link = os.path.join(self.directory, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.basename(path), link)
        except OSError:
            pass

    def _gc(self) -> None:
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            try:
                shutil.rmtree(self._step_path(victim))
            except OSError:
                pass
