"""Checkpoint/resume: durable training state with atomic installs.

The reference leaves checkpoint I/O to the application (``torch.save`` to
tmp+rename, leader-only, ``examples/vtrace/experiment.py:186-204,439-468``)
and provides the cohort-sync hooks (``Accumulator.set_state/state``,
``set_model_version``).  Here the framework owns the I/O too:

- :class:`Checkpointer` — orbax-backed when available (async-capable,
  sharding-aware: restores resharded arrays directly onto a mesh), with a
  pickle fallback; atomic installs either way; retains the last N.
- Integrity is first-class (docs/RESILIENCE.md): every ``step_<N>/``
  carries a ``manifest.json`` (step, file list, sizes, sha256) written
  before the atomic rename, so a checkpoint is either whole or
  detectably partial.  ``restore()`` validates the manifest and, on
  corruption/truncation, *falls back to the newest intact older
  checkpoint* instead of raising — logging what it skipped and bumping
  the ``checkpoint_corrupt_skipped`` telemetry counter.  ``all_steps()``
  ignores manifest-less partial directories for the same reason.
- The cohort-sync side stays on the Accumulator exactly like the reference:
  restore → ``accumulator.set_model_version(step)`` so leader election
  prefers the restored peer.
- :class:`DistributedCheckpointer` — the pod-scale plane on top of the
  same integrity machinery: every cohort member writes its own byte-range
  shard(s) of the deterministic full-state blob plus a per-host manifest,
  and the leader commits a cohort manifest via TWO-PHASE commit
  (``cohort_manifest.json.pending`` → atomic rename), so a torn
  checkpoint — host killed mid-shard-write, leader killed between the
  phases — is never eligible for restore.  Capture is asynchronous and
  double-buffered (``copy_to_host_async`` + a background writer thread;
  ``checkpoint_stall_seconds`` / ``checkpoint_write_seconds`` prove the
  train step is not blocked), and restore is elastic: an N-host checkpoint
  assembles bit-exact on an M-host cohort, re-cutting shard slices with
  ``buckets.shard_ranges`` (docs/RESILIENCE.md "Distributed checkpoints").
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import queue as queue_mod
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from . import buckets, telemetry, utils

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except ImportError:  # pragma: no cover
    ocp = None
    _HAS_ORBAX = False

_REG = telemetry.get_registry()
_M_CORRUPT_SKIPPED = _REG.counter(
    "checkpoint_corrupt_skipped",
    "corrupt/partial checkpoints skipped by restore() fallback",
)
_M_STALL = _REG.histogram(
    "checkpoint_stall_seconds",
    "train-thread blocked seconds per async capture handoff (D2H issue + staging)",
)
_M_WRITE = _REG.histogram(
    "checkpoint_write_seconds",
    "background seconds per shard capture (device fetch + pickle + file write)",
)
_M_SHARD_BYTES = _REG.counter(
    "checkpoint_shard_bytes_total", "checkpoint shard payload bytes written"
)
_M_COMMITS = _REG.counter(
    "checkpoint_commits_total",
    "cohort manifests committed (two-phase commit completed)",
)
_M_DECLINED = _REG.counter(
    "checkpoint_captures_declined_total",
    "async captures declined because both staging slots were busy",
)
_M_RECONSTRUCTED = _REG.counter(
    "checkpoint_shard_reconstructions_total",
    "shard byte ranges rebuilt from a replica copy during restore",
)

_MANIFEST = "manifest.json"
_COHORT_MANIFEST = "cohort_manifest.json"
_PENDING = _COHORT_MANIFEST + ".pending"
# Chaos knob (scripts/chaos_soak.py): seconds to hold each shard's tmp file
# before its atomic rename, widening the mid-shard-write window the soak's
# SIGKILL targets.  Never set outside fault-injection harnesses.
_WRITE_DELAY_ENV = "MOOLIB_CKPT_WRITE_DELAY"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def canonical_tree(tree: Any) -> Any:
    """Rebuild ``tree`` with plain-dict keys in sorted order, recursively.

    Replicated state must pickle to identical bytes on every host, but dict
    *insertion* order is a host-local artifact: a tree rebuilt from a jax
    flatten/unflatten round-trip iterates keys sorted, while one that arrived
    through a pickle-based model sync keeps its original order.  Same values,
    different bytes, different digest.  Sorting matches jax's own dict-key
    flatten convention, so restored trees are semantically unchanged.
    """
    if type(tree) is dict:
        return {k: canonical_tree(tree[k]) for k in sorted(tree)}
    if isinstance(tree, tuple):
        vals = [canonical_tree(v) for v in tree]
        if hasattr(tree, "_fields"):  # NamedTuple
            return type(tree)(*vals)
        return tuple(vals)
    if type(tree) is list:
        return [canonical_tree(v) for v in tree]
    return tree


class Checkpointer:
    """Save/restore arbitrary pytrees of arrays + metadata under a directory.

    Layout: ``<dir>/step_<N>/`` per checkpoint (each with a
    ``manifest.json`` integrity record) plus a ``latest`` symlink.
    """

    def __init__(self, directory: str, max_to_keep: int = 3, use_orbax: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._use_orbax = _HAS_ORBAX if use_orbax is None else (use_orbax and _HAS_ORBAX)
        self._ckptr = ocp.PyTreeCheckpointer() if self._use_orbax else None
        self._warned_partial: set = set()  # manifest-less dirs already logged

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any) -> str:
        """Write a checkpoint for ``step``; returns its path. Atomic: partial
        writes land in a tmp dir (manifest included) that is renamed into
        place — a crash mid-save can only ever leave a ``.tmp`` husk."""
        path = self._step_path(step)
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        host_state = jax.device_get(state)
        if self._use_orbax:
            self._ckptr.save(tmp, host_state)
        else:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
        self._write_manifest(tmp, step)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        self._update_latest(path)
        self._gc()
        utils.log_info("checkpoint: saved step %d to %s", step, path)
        return path

    def _write_manifest(self, tmp: str, step: int) -> None:
        files: Dict[str, Dict[str, object]] = {}
        for root, _dirs, names in os.walk(tmp):
            for name in names:
                if name == _MANIFEST:
                    continue
                full = os.path.join(root, name)
                rel = os.path.relpath(full, tmp)
                files[rel] = {"size": os.path.getsize(full), "sha256": _sha256(full)}
        manifest = {
            "step": int(step),
            "format": "orbax" if self._use_orbax else "pickle",
            "time": time.time(),
            "files": files,
        }
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)

    # --------------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None, target: Any = None) -> Optional[Any]:
        """Load the newest *intact* checkpoint (≤ ``step`` when given);
        None if none exists.

        A candidate whose manifest is missing, unparsable, or whose files
        fail the size/sha256 check — or whose payload fails to deserialize
        — is logged, counted (``checkpoint_corrupt_skipped``) and skipped
        in favor of the next older one: a torn write or a truncated disk
        must cost one checkpoint interval, not the run.

        With orbax and a ``target`` pytree of sharded arrays, restored
        leaves land directly with the target's shardings (no host round
        trip on the user side).
        """
        candidates = self.all_steps()
        if step is not None:
            candidates = [s for s in candidates if s <= step]
            if not candidates or candidates[-1] != step:
                utils.log_error(
                    "checkpoint: step %s missing or partial under %s",
                    step, self.directory,
                )
        for cand in reversed(candidates):
            path = self._step_path(cand)
            reason = self._verify(path)
            if reason is not None:
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping corrupt %s (%s); falling back", path, reason
                )
                telemetry.flight_event(
                    "checkpoint.corrupt_skipped", path=path, reason=reason
                )
                continue
            try:
                return self._load(path, target)
            except Exception as e:  # noqa: BLE001 — treat as corruption
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping unreadable %s (%r); falling back", path, e
                )
                telemetry.flight_event(
                    "checkpoint.corrupt_skipped", path=path, reason=repr(e)
                )
        return None

    def _load(self, path: str, target: Any):
        is_pickle = os.path.exists(os.path.join(path, "state.pkl"))
        if not is_pickle:
            if not self._use_orbax:
                raise RuntimeError(
                    f"checkpoint {path} was written by orbax but orbax is "
                    "unavailable here (install orbax-checkpoint or restore "
                    "on the saving host)"
                )
            if target is not None:
                return self._ckptr.restore(path, item=target)
            return self._ckptr.restore(path)
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def _verify(self, path: str) -> Optional[str]:
        """None when ``path`` matches its manifest; else a human reason."""
        mpath = os.path.join(path, _MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return f"manifest unreadable: {e}"
        files = manifest.get("files")
        if not isinstance(files, dict):
            return "manifest has no file table"
        for rel, meta in files.items():
            full = os.path.join(path, rel)
            if not os.path.exists(full):
                return f"missing file {full}"
            size = os.path.getsize(full)
            if size != meta.get("size"):
                return f"truncated {full} ({size} != {meta.get('size')} bytes)"
            actual = _sha256(full)
            if actual != meta.get("sha256"):
                # Name the file AND both digests: the triage path for a bad
                # disk/torn write starts from exactly this line.
                return (
                    f"checksum mismatch on {full}: "
                    f"expected {meta.get('sha256')}, got {actual}"
                )
        return None

    def verify(self, step: int) -> bool:
        """Public integrity probe: does ``step`` exist and match its
        manifest byte-for-byte?"""
        return self._verify(self._step_path(step)) is None

    def all_steps(self) -> List[int]:
        """Steps with a manifest present.  A ``step_<N>/`` without one is a
        partial artifact (pre-rename husk, hand-damaged, or written by a
        pre-manifest version) and is ignored — it must never be selected as
        'latest'.  Skips are logged once per directory so a legacy
        checkpoint dir can't silently read as empty."""
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                if not os.path.exists(os.path.join(self.directory, name, _MANIFEST)):
                    if name not in self._warned_partial:
                        self._warned_partial.add(name)
                        utils.log_error(
                            "checkpoint: ignoring %s/%s (no %s — partial or "
                            "pre-manifest; re-save to adopt it)",
                            self.directory, name, _MANIFEST,
                        )
                    continue
                try:
                    steps.append(int(name[len("step_") :]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_intact_step(self) -> Optional[int]:
        """The newest step whose manifest verifies byte-for-byte — the step
        ``restore()`` would actually load.  Warm-rejoin callers use this to
        learn the version a restart will advertise (and chaos harnesses to
        predict the resume point after a truncation) WITHOUT paying the
        payload deserialization."""
        for step in reversed(self.all_steps()):
            if self._verify(self._step_path(step)) is None:
                return step
        return None

    # ------------------------------------------------------------- internals
    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def _update_latest(self, path: str) -> None:
        link = os.path.join(self.directory, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.basename(path), link)
        except OSError:
            pass

    def _gc(self) -> None:
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            try:
                shutil.rmtree(self._step_path(victim))
            except OSError:
                pass


# --------------------------------------------------------------------------
# Distributed (cohort) checkpoints
# --------------------------------------------------------------------------
class MissingShardError(RuntimeError):
    """A committed cohort checkpoint cannot be assembled: some byte ranges
    are missing or corrupt in EVERY surviving copy.  Carries the offending
    ``(owner_rank, start, stop)`` ranges so the error names exactly which
    host's artifacts are gone — ``spec="sharded"`` cohorts have no replicas
    to rebuild from, so this is their terminal restore failure."""

    def __init__(self, step: int, missing: Sequence[Tuple[int, int, int]]):
        self.step = int(step)
        self.missing = [(int(r), int(a), int(b)) for r, a, b in missing]
        detail = ", ".join(
            f"rank {r} bytes [{a}:{b})" for r, a, b in self.missing
        )
        super().__init__(f"checkpoint step {step}: missing shards ({detail})")


def shard_plan(total_bytes: int, world: int, spec: str = "replicated"):
    """Byte-range shard layout for a ``world``-host cohort.

    Rank *i* owns range *i* of ``buckets.shard_ranges(total_bytes, world)``
    and — under ``spec="replicated"`` — also writes a replica of range
    ``(i+1) % world``, so any single host's artifacts can be rebuilt from
    survivors.  Returns ``(ranges, owned)`` where ``owned[rank]`` lists the
    range indices that rank writes (own range first)."""
    ranges = buckets.shard_ranges(int(total_bytes), int(world), 1)
    owned = []
    for rank in range(int(world)):
        mine = [rank]
        if spec == "replicated" and int(world) > 1:
            mine.append((rank + 1) % int(world))
        owned.append(mine)
    return ranges, owned


def _write_json_atomic(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class DistributedCheckpointer:
    """Pod-consistent sharded checkpoints with two-phase commit.

    Each cohort member holds the full replicated training state (the
    sharded-allreduce plane all-gathers true sums, so host-level state is
    identical — the same determinism ``Accumulator._sync_chunks``
    documents).  A snapshot therefore shards the deterministic pickle blob
    BY BYTE RANGE: host *i* writes only its ~1/N slice (plus one replica
    slice under ``spec="replicated"``), cutting per-host checkpoint I/O by
    the cohort size while the union remains the bit-exact full state.

    On-disk layout per checkpoint (``<dir>/step_<N>/``):

    - ``shard_<rank>_<range>.bin`` — byte range ``<range>`` of the blob,
      written by host ``<rank>`` (tmp + fsync + atomic rename).
    - ``manifest_<rank>.json`` — per-host manifest: rank, world, spec,
      blob sha256, and the size/sha256 of each range file that rank wrote.
    - ``cohort_manifest.json`` — the leader's commit record (step,
      membership epoch, world, shard map, per-file sha256).  Written as
      ``cohort_manifest.json.pending`` first (phase 1, fsynced) and
      atomically renamed (phase 2): a checkpoint is eligible for restore
      IFF this file exists, so a host SIGKILLed mid-shard-write or a
      leader killed between the phases leaves nothing restorable — a torn
      checkpoint costs one interval, never a bad restore.

    Restore is elastic: assembly only needs the committed range files, so
    an N-host checkpoint restores bit-exact onto any M-host cohort;
    :meth:`restore_slice` re-cuts this host's byte slice for the NEW
    cohort size via ``buckets.shard_ranges`` (warm-rejoin slice serving,
    ``Accumulator.preload_sync_slice``).  A missing range is rebuilt from
    a replica copy (``checkpoint_shard_reconstructions_total``) when one
    survives, else :class:`MissingShardError` names it.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        spec: str = "replicated",
        watchdog=None,
        write_timeout: float = 120.0,
    ):
        if spec not in ("replicated", "sharded"):
            raise ValueError(f"unknown shard spec {spec!r}")
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.spec = spec
        self._wd = watchdog
        self._write_timeout = write_timeout
        # Async capture plane: double-buffered staging — at most two
        # captures (one writing + one queued) ride the background worker; a
        # third is declined (checkpoint_captures_declined_total) instead of
        # queueing unboundedly behind a slow filesystem.
        self._slot_lock = threading.Lock()
        self._busy = 0
        self._queue: Optional[queue_mod.Queue] = None
        self._worker: Optional[threading.Thread] = None
        # Rolling capture accounting for the examples' exit summary line
        # (the chaos soak gates stall-vs-step-time on it); the registry
        # histograms above carry the same numbers for exporters.
        self._cap_stats = {
            "captures": 0, "stall_s": 0.0, "write_s": 0.0, "commits": 0,
        }
        # (step, sha16, blob) of the newest successful restore; the
        # accumulator auto-registers it as a warm-rejoin sync slice.
        self.last_restored: Optional[Tuple[int, str, bytes]] = None

    def set_watchdog(self, watchdog) -> None:
        """Attach (or replace) the watchdog whose ``section()`` arms around
        shard file writes — a hung filesystem write fires
        ``dump_diagnostics`` instead of silently wedging the writer."""
        self._wd = watchdog

    def stats(self) -> Dict[str, float]:
        """Capture-side accounting: ``captures``, ``stall_s`` (train-thread
        blocked seconds), ``write_s`` (background seconds), ``commits``."""
        with self._slot_lock:
            return dict(self._cap_stats)

    def _section(self, name: str):
        if self._wd is not None:
            return self._wd.section(name, self._write_timeout)
        return contextlib.nullcontext()

    # ------------------------------------------------------------ write side
    def write_shard(self, step: int, blob: bytes, rank: int, world: int,
                    epoch=0) -> Dict[str, Any]:
        """Write this host's shard file(s) + per-host manifest for ``step``
        and return the report dict the leader's commit consumes.

        Synchronous (the train loop uses :meth:`begin_capture` instead);
        every file lands tmp + fsync + atomic rename, so a kill mid-write
        leaves only ``.tmp`` husks that no manifest references."""
        step, rank, world = int(step), int(rank), int(world)
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside cohort of {world}")
        sdir = self._step_path(step)
        os.makedirs(sdir, exist_ok=True)
        ranges, owned = shard_plan(len(blob), world, self.spec)
        delay = float(os.environ.get(_WRITE_DELAY_ENV, "0") or 0.0)
        files: Dict[str, Dict[str, Any]] = {}
        for j in owned[rank]:
            a, b = ranges[j]
            fname = f"shard_{rank}_{j}.bin"
            full = os.path.join(sdir, fname)
            tmp = full + ".tmp"
            # Satellite: a wedged filesystem write must fire diagnostics,
            # not silently hold the background writer forever.
            with self._section("checkpoint_shard_write"):
                with open(tmp, "wb") as f:
                    f.write(blob[a:b])
                    f.flush()
                    os.fsync(f.fileno())
                if delay:
                    time.sleep(delay)  # chaos knob: hold the torn window open
                os.replace(tmp, full)
            files[fname] = {
                "range": j, "start": a, "stop": b, "size": b - a,
                "sha256": hashlib.sha256(blob[a:b]).hexdigest(),
            }
            _M_SHARD_BYTES.inc(b - a)
        report = {
            "step": step, "rank": rank, "world": world, "epoch": epoch,
            "spec": self.spec, "total_bytes": len(blob),
            "blob_sha256": hashlib.sha256(blob).hexdigest(), "files": files,
        }
        with self._section("checkpoint_shard_write"):
            _write_json_atomic(
                os.path.join(sdir, f"manifest_{rank}.json"), report
            )
        return report

    def begin_capture(self, *, step: int, rank: int, world: int, state,
                      epoch=0, on_done=None) -> bool:
        """Async, non-stalling capture of ``state`` (any pytree) into this
        host's shard files.

        The caller's thread only issues ``copy_to_host_async`` on the
        device leaves and enqueues the work — that handoff is the whole
        train-step cost, measured as ``checkpoint_stall_seconds``.  A
        background worker completes the transfers, pickles, shards, and
        writes (``checkpoint_write_seconds``), then calls
        ``on_done(report_or_None)`` from its own thread.  Returns False
        (``checkpoint_captures_declined_total``) when both staging slots
        are busy — the snapshot is skipped, never queued unboundedly."""
        t0 = time.monotonic()
        with self._slot_lock:
            if self._busy >= 2:
                _M_DECLINED.inc()
                return False
            self._busy += 1
            self._ensure_worker_locked()
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        self._queue.put((int(step), int(rank), int(world), epoch, state, on_done))
        dt = time.monotonic() - t0
        _M_STALL.observe(dt)
        with self._slot_lock:
            self._cap_stats["captures"] += 1
            self._cap_stats["stall_s"] += dt
        return True

    def _ensure_worker_locked(self) -> None:
        if self._worker is None:
            self._queue = queue_mod.Queue()
            self._worker = threading.Thread(
                target=self._worker_main, name="ckpt-shard-writer", daemon=True
            )
            self._worker.start()

    def _worker_main(self) -> None:
        q = self._queue
        while True:
            item = q.get()
            if item is None:
                return
            step, rank, world, epoch, state, on_done = item
            t0 = time.monotonic()
            report = None
            try:
                host = canonical_tree(jax.device_get(state))
                blob = pickle.dumps(host, protocol=pickle.HIGHEST_PROTOCOL)
                report = self.write_shard(step, blob, rank, world, epoch=epoch)
            except Exception as e:  # noqa: BLE001 — capture must not kill the worker
                utils.log_error(
                    "checkpoint: shard capture for step %s failed: %r", step, e
                )
            finally:
                dt = time.monotonic() - t0
                _M_WRITE.observe(dt)
                with self._slot_lock:
                    self._busy -= 1
                    self._cap_stats["write_s"] += dt
            if on_done is not None:
                try:
                    on_done(report)
                except Exception as e:  # noqa: BLE001 — hook bugs stay local
                    utils.log_error("checkpoint: on_done hook failed: %r", e)

    def close(self) -> None:
        """Stop the background writer (daemonized anyway; this makes
        teardown deterministic in tests)."""
        with self._slot_lock:
            q, self._worker = self._queue, None
            self._queue = None
        if q is not None:
            q.put(None)

    # ----------------------------------------------------- two-phase commit
    def prepare_commit(self, step: int, reports: Sequence[Dict[str, Any]]) -> str:
        """Phase 1 (leader): validate the quorum and stage the cohort
        manifest as ``cohort_manifest.json.pending`` (fsynced).

        Every rank ``0..world-1`` must have reported, and every report must
        agree on the blob digest/size/epoch — digest agreement IS the
        version-consistency proof for the snapshot.  The checkpoint is NOT
        yet eligible for restore after this phase."""
        step = int(step)
        reports = [r for r in reports if r]
        if not reports:
            raise ValueError(f"checkpoint step {step}: no shard reports")
        world = int(reports[0]["world"])
        sha = reports[0]["blob_sha256"]
        total = int(reports[0]["total_bytes"])
        epoch = reports[0].get("epoch")
        spec = reports[0].get("spec", self.spec)
        ranks = sorted(int(r["rank"]) for r in reports)
        if ranks != list(range(world)):
            raise ValueError(
                f"checkpoint step {step}: quorum incomplete "
                f"(have ranks {ranks}, want 0..{world - 1})"
            )
        for r in reports:
            key = (r["blob_sha256"], int(r["total_bytes"]), int(r["world"]),
                   r.get("epoch"))
            if key != (sha, total, world, epoch):
                raise ValueError(
                    f"checkpoint step {step}: rank {r['rank']} digest/shape "
                    f"disagrees — snapshot not version-consistent (rank 0: "
                    f"sha {sha[:16]} {total} B epoch {epoch}; "
                    f"rank {r['rank']}: sha {r['blob_sha256'][:16]} "
                    f"{int(r['total_bytes'])} B epoch {r.get('epoch')})"
                )
        cohort = {
            "step": step, "epoch": epoch, "world": world, "spec": spec,
            "total_bytes": total, "blob_sha256": sha, "time": time.time(),
            "shards": {
                str(int(r["rank"])): {"files": r["files"]} for r in reports
            },
        }
        sdir = self._step_path(step)
        os.makedirs(sdir, exist_ok=True)
        pending = os.path.join(sdir, _PENDING)
        _write_json_atomic(pending, cohort)
        return pending

    def commit(self, step: int) -> str:
        """Phase 2 (leader): atomically promote the pending cohort manifest
        — the single instant the checkpoint becomes eligible for restore."""
        sdir = self._step_path(int(step))
        pending = os.path.join(sdir, _PENDING)
        final = os.path.join(sdir, _COHORT_MANIFEST)
        if not os.path.exists(pending):
            raise FileNotFoundError(
                f"checkpoint step {step}: no pending cohort manifest to commit"
            )
        os.replace(pending, final)
        _fsync_dir(sdir)
        _M_COMMITS.inc()
        with self._slot_lock:
            self._cap_stats["commits"] += 1
        telemetry.flight_event(
            "checkpoint.cohort_committed", step=int(step), path=final
        )
        utils.log_info("checkpoint: committed cohort manifest %s", final)
        self._gc()
        return final

    def commit_cohort(self, step: int, reports: Sequence[Dict[str, Any]]) -> str:
        """Both phases back to back (the leader's normal path)."""
        self.prepare_commit(step, reports)
        return self.commit(step)

    # -------------------------------------------------------------- restore
    def committed_steps(self) -> List[int]:
        """Steps whose cohort manifest is COMMITTED (phase 2 done).  A
        ``.pending``-only or manifest-less ``step_<N>/`` is a torn artifact
        and is never eligible."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if name.startswith("step_") and not name.endswith(".tmp"):
                if os.path.exists(
                    os.path.join(self.directory, name, _COHORT_MANIFEST)
                ):
                    try:
                        steps.append(int(name[len("step_"):]))
                    except ValueError:
                        pass
        return sorted(steps)

    def latest_committed_step(self) -> Optional[int]:
        steps = self.committed_steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None) -> Optional[Tuple[int, Any]]:
        """Load the newest committed, assemblable checkpoint (≤ ``step``
        when given) as ``(step, state)``; None when nothing is committed.

        A committed candidate whose files are truncated/corrupt falls back
        to the next older committed one (``checkpoint_corrupt_skipped``),
        after replica reconstruction has been tried.  When at least one
        candidate failed ONLY for missing shards and no older checkpoint
        could be restored, the :class:`MissingShardError` naming those
        shards is raised instead of silently returning None."""
        candidates = self.committed_steps()
        if step is not None:
            candidates = [s for s in candidates if s <= step]
        missing_err = None
        for cand in reversed(candidates):
            path = self._step_path(cand)
            try:
                blob, cohort = self._assemble(cand)
                state = pickle.loads(blob)
            except MissingShardError as e:
                missing_err = missing_err or e
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping committed %s (%s); falling back",
                    path, e,
                )
                telemetry.flight_event(
                    "checkpoint.corrupt_skipped", path=path, reason=str(e)
                )
                continue
            except Exception as e:  # noqa: BLE001 — treat as corruption
                _M_CORRUPT_SKIPPED.inc()
                utils.log_error(
                    "checkpoint: skipping corrupt %s (%r); falling back",
                    path, e,
                )
                telemetry.flight_event(
                    "checkpoint.corrupt_skipped", path=path, reason=repr(e)
                )
                continue
            self.last_restored = (cand, cohort["blob_sha256"][:16], blob)
            return cand, state
        if missing_err is not None:
            raise missing_err
        return None

    def restore_slice(
        self, rank: int, world: int, step: Optional[int] = None
    ) -> Optional[Tuple[int, str, int, bytes, int]]:
        """Elastic re-cut: assemble the newest committed blob and return
        ``(step, sha16, start, data, total_bytes)`` — THIS host's byte
        slice under a ``world``-host layout (``buckets.shard_ranges``),
        regardless of the cohort size that wrote the checkpoint.  Feeds
        ``Accumulator.preload_sync_slice`` so a rejoining host pulls only
        the bytes it does not already hold."""
        candidates = self.committed_steps()
        if step is not None:
            candidates = [s for s in candidates if s <= step]
        if not candidates:
            return None
        cand = candidates[-1]
        blob, cohort = self._assemble(cand)
        a, b = buckets.shard_ranges(len(blob), int(world), 1)[int(rank)]
        return cand, cohort["blob_sha256"][:16], a, blob[a:b], len(blob)

    def verify(self, step: int) -> bool:
        """Public probe: is ``step`` committed AND assemblable bit-exact?"""
        try:
            self._assemble(int(step))
        except Exception:  # noqa: BLE001 — any failure means not restorable
            return False
        return True

    def _assemble(self, step: int) -> Tuple[bytes, Dict[str, Any]]:
        sdir = self._step_path(int(step))
        with open(os.path.join(sdir, _COHORT_MANIFEST)) as f:
            cohort = json.load(f)
        total = int(cohort["total_bytes"])
        world = int(cohort["world"])
        buf = bytearray(total)
        # Candidate files per range, primary (owner rank == range) first so
        # replica reads are countable reconstructions, not the normal path.
        by_range: Dict[int, List[Tuple[bool, str, Dict[str, Any]]]] = {}
        for rank_s, shard in cohort.get("shards", {}).items():
            for fname, meta in shard.get("files", {}).items():
                j = int(meta["range"])
                by_range.setdefault(j, []).append(
                    (int(rank_s) != j, fname, meta)
                )
        missing = []
        for j, (a, b) in enumerate(buckets.shard_ranges(total, world, 1)):
            done = False
            for is_replica, fname, meta in sorted(
                by_range.get(j, []), key=lambda t: (t[0], t[1])
            ):
                full = os.path.join(sdir, fname)
                reason = _verify_range_file(full, meta, b - a)
                if reason is not None:
                    utils.log_error("checkpoint: %s", reason)
                    continue
                with open(full, "rb") as f:
                    buf[a:b] = f.read()
                if is_replica:
                    _M_RECONSTRUCTED.inc()
                    utils.log_info(
                        "checkpoint: step %d range %d rebuilt from replica "
                        "%s (primary lost)", int(step), j, fname,
                    )
                done = True
                break
            if not done:
                missing.append((j, a, b))
        if missing:
            raise MissingShardError(step, missing)
        got = hashlib.sha256(bytes(buf)).hexdigest()
        if got != cohort["blob_sha256"]:
            raise ValueError(
                f"assembled blob checksum mismatch for step {step}: "
                f"expected {cohort['blob_sha256']}, got {got}"
            )
        return bytes(buf), cohort

    # ------------------------------------------------------------- internals
    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step)}")

    def _gc(self) -> None:
        committed = self.committed_steps()
        victims = committed[:-self.max_to_keep] if self.max_to_keep else []
        newest = committed[-1] if committed else None
        for s in victims:
            shutil.rmtree(self._step_path(s), ignore_errors=True)
        # Torn husks: step dirs that never committed and are OLDER than the
        # newest committed checkpoint can never become eligible — reap them.
        # (Newer uncommitted dirs may be mid-write and are left alone.)
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not name.startswith("step_"):
                continue
            base = name[:-len(".tmp")] if name.endswith(".tmp") else name
            try:
                s = int(base[len("step_"):])
            except ValueError:
                continue
            committed_here = os.path.exists(
                os.path.join(self.directory, name, _COHORT_MANIFEST)
            )
            if newest is not None and s < newest and not committed_here:
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )


def _verify_range_file(full: str, meta: Dict[str, Any], want_size: int):
    """None when the range file matches its manifest entry; else the reason
    with path + expected/actual digests (triage starts from this string)."""
    if not os.path.exists(full):
        return f"missing shard file {full}"
    size = os.path.getsize(full)
    if size != want_size:
        return f"truncated shard {full} ({size} != {want_size} bytes)"
    actual = _sha256(full)
    if actual != meta.get("sha256"):
        return (
            f"checksum mismatch on {full}: "
            f"expected {meta.get('sha256')}, got {actual}"
        )
    return None
