"""Checkpoint/resume: durable training state with atomic installs.

The reference leaves checkpoint I/O to the application (``torch.save`` to
tmp+rename, leader-only, ``examples/vtrace/experiment.py:186-204,439-468``)
and provides the cohort-sync hooks (``Accumulator.set_state/state``,
``set_model_version``).  Here the framework owns the I/O too:

- :class:`Checkpointer` — orbax-backed when available (async-capable,
  sharding-aware: restores resharded arrays directly onto a mesh), with a
  pickle fallback; atomic installs either way; retains the last N.
- The cohort-sync side stays on the Accumulator exactly like the reference:
  restore → ``accumulator.set_model_version(step)`` so leader election
  prefers the restored peer.
"""

from __future__ import annotations

import os
import pickle
import shutil
import time
from typing import Any, List, Optional

import jax

from . import utils

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except ImportError:  # pragma: no cover
    ocp = None
    _HAS_ORBAX = False


class Checkpointer:
    """Save/restore arbitrary pytrees of arrays + metadata under a directory.

    Layout: ``<dir>/step_<N>/`` per checkpoint plus a ``latest`` symlink.
    """

    def __init__(self, directory: str, max_to_keep: int = 3, use_orbax: Optional[bool] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._use_orbax = _HAS_ORBAX if use_orbax is None else (use_orbax and _HAS_ORBAX)
        self._ckptr = ocp.PyTreeCheckpointer() if self._use_orbax else None

    # ------------------------------------------------------------------ save
    def save(self, step: int, state: Any) -> str:
        """Write a checkpoint for ``step``; returns its path. Atomic: partial
        writes land in a tmp dir that is renamed into place."""
        path = self._step_path(step)
        tmp = path + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        host_state = jax.device_get(state)
        if self._use_orbax:
            self._ckptr.save(tmp, host_state)
        else:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        self._update_latest(path)
        self._gc()
        utils.log_info("checkpoint: saved step %d to %s", step, path)
        return path

    # --------------------------------------------------------------- restore
    def restore(self, step: Optional[int] = None, target: Any = None) -> Optional[Any]:
        """Load a checkpoint (latest by default); None if none exist.

        With orbax and a ``target`` pytree of sharded arrays, restored leaves
        land directly with the target's shardings (no host round trip on the
        user side).
        """
        if step is None:
            steps = self.all_steps()
            if not steps:
                return None
            step = steps[-1]
        path = self._step_path(step)
        if not os.path.exists(path):
            return None
        is_pickle = os.path.exists(os.path.join(path, "state.pkl"))
        if not is_pickle:
            if not self._use_orbax:
                raise RuntimeError(
                    f"checkpoint {path} was written by orbax but orbax is "
                    "unavailable here (install orbax-checkpoint or restore "
                    "on the saving host)"
                )
            if target is not None:
                return self._ckptr.restore(path, item=target)
            return self._ckptr.restore(path)
        with open(os.path.join(path, "state.pkl"), "rb") as f:
            return pickle.load(f)

    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    steps.append(int(name[len("step_") :]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------- internals
    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def _update_latest(self, path: str) -> None:
        link = os.path.join(self.directory, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.basename(path), link)
        except OSError:
            pass

    def _gc(self) -> None:
        steps = self.all_steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            try:
                shutil.rmtree(self._step_path(victim))
            except OSError:
                pass
