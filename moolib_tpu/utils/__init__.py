"""Foundation utilities for moolib_tpu.

TPU-native counterparts of the reference's layer-1 utilities
(``src/util.h:1-214``, ``src/logging.h:27-106``): uid generation, timing,
leveled logging with a Python-logging bridge, and stats counters.
"""

from __future__ import annotations

import logging as _pylogging
import secrets
import time
from typing import Optional

from . import nest  # noqa: F401
from .stats import RunningMeanStd, StatMean, StatSum  # noqa: F401
from .compile_cache import compile_cache_dir, init_compile_cache  # noqa: F401

# ---------------------------------------------------------------------------
# uid / naming  (reference: randomName(), src/util.h — 16 hex chars)
# ---------------------------------------------------------------------------


def create_uid() -> str:
    """Return a random 16-hex-char uid, like the reference's ``create_uid``."""
    return secrets.token_hex(8)


random_name = create_uid

# ---------------------------------------------------------------------------
# logging  (reference: moolib::log levels none/error/info/verbose/debug,
#           optional routing into Python logging via set_logging)
# ---------------------------------------------------------------------------

LOG_NONE = 0
LOG_ERROR = 1
LOG_INFO = 2
LOG_VERBOSE = 3
LOG_DEBUG = 4

_LEVELS = {
    "none": LOG_NONE,
    "error": LOG_ERROR,
    "info": LOG_INFO,
    "verbose": LOG_VERBOSE,
    "debug": LOG_DEBUG,
}

_log_level = LOG_ERROR
_py_logger: Optional[_pylogging.Logger] = None


def set_log_level(level) -> None:
    """Set the global log level ("none"|"error"|"info"|"verbose"|"debug")."""
    global _log_level
    if isinstance(level, str):
        level = _LEVELS[level.lower()]
    _log_level = int(level)


def set_logging(logger=None) -> None:
    """Route moolib_tpu logs into a Python ``logging``-style logger.

    Mirrors the reference's ``set_logging(logging)`` which accepts the
    ``logging`` module itself or a logger object.
    """
    global _py_logger
    if logger is None:
        _py_logger = None
    elif hasattr(logger, "info"):
        _py_logger = logger
    else:  # the logging module itself
        _py_logger = _pylogging.getLogger("moolib_tpu")


def _emit(level: int, msg: str, *args) -> None:
    if level > _log_level:
        return
    if args:
        msg = msg % args
    if _py_logger is not None:
        if level <= LOG_ERROR:
            _py_logger.error(msg)
        elif level == LOG_INFO:
            _py_logger.info(msg)
        else:
            _py_logger.debug(msg)
    else:
        ts = time.strftime("%H:%M:%S")
        print(f"[{ts}] moolib_tpu: {msg}", flush=True)


def log_error(msg: str, *args) -> None:
    _emit(LOG_ERROR, msg, *args)


def log_info(msg: str, *args) -> None:
    _emit(LOG_INFO, msg, *args)


def log_verbose(msg: str, *args) -> None:
    _emit(LOG_VERBOSE, msg, *args)


def log_debug(msg: str, *args) -> None:
    _emit(LOG_DEBUG, msg, *args)


# ---------------------------------------------------------------------------
# scheduler sizing  (reference: set_max_threads → async scheduler cap)
# ---------------------------------------------------------------------------

_max_threads: Optional[int] = None


def set_max_threads(n: int) -> None:
    """Cap worker threads used by Rpc executors (reference: set_max_threads)."""
    global _max_threads
    _max_threads = int(n)


def get_max_threads() -> Optional[int]:
    return _max_threads


# ---------------------------------------------------------------------------
# Timer  (reference: moolib::Timer, src/util.h:50-68)
# ---------------------------------------------------------------------------


def apply_platform_env() -> None:
    """Honor the JAX_PLATFORMS env var even when a sitecustomize imported jax
    at interpreter start (which locks the env-var-based selection). Call at
    the top of CLI entry points, before any jax computation."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception:  # backends already initialized; keep whatever exists
        pass


class Timer:
    """Monotonic elapsed-seconds timer."""

    def __init__(self):
        self._start = time.monotonic()

    def reset(self) -> None:
        self._start = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._start

    def elapsed_reset(self) -> float:
        now = time.monotonic()
        out = now - self._start
        self._start = now
        return out
