"""Persistent XLA compile cache wiring (docs/RESILIENCE.md "Recovery budget").

A restarted peer's recovery time is dominated by two costs: re-acquiring the
model (chunked sync, ``accumulator.py``) and re-running XLA compilation of
its train step from scratch.  The second cost is pure waste — the restarted
process compiles the *same* programs its previous incarnation already
compiled — and jax ships the fix: a persistent on-disk compilation cache
(``jax_compilation_cache_dir``).  This module is the ONE place that wires
it, so every entry point (the three examples, soak/chaos children, respawned
EnvPool workers) applies identical knobs:

- ``MOOLIB_COMPILE_CACHE=<dir>`` — enable the cache at ``<dir>`` (the soak
  harness points every peer at one shared directory: peer 0 compiles, the
  other N-1 cold starts and every kill/restart reload from disk).
- ``--compile_cache_dir`` on the example CLIs — same knob, explicit arg
  wins over the environment.
- ``MOOLIB_COMPILE_CACHE_MIN_COMPILE_SECS`` (default ``0.5``) — only
  persist compilations that took at least this long; tiny programs aren't
  worth the disk round trip.
- ``MOOLIB_COMPILE_CACHE_MIN_ENTRY_BYTES`` (default ``0``) — minimum
  serialized-executable size to persist.

``init_compile_cache()`` is idempotent and deliberately import-light: with
no directory configured it returns ``None`` without importing jax, so the
EnvPool worker main (which normally never touches jax) stays jax-free
unless the operator opted in.
"""

from __future__ import annotations

import os
from typing import Optional

_ENV_DIR = "MOOLIB_COMPILE_CACHE"
_ENV_MIN_SECS = "MOOLIB_COMPILE_CACHE_MIN_COMPILE_SECS"
_ENV_MIN_BYTES = "MOOLIB_COMPILE_CACHE_MIN_ENTRY_BYTES"

_initialized_dir: Optional[str] = None


def init_compile_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at ``cache_dir`` (or
    ``$MOOLIB_COMPILE_CACHE`` when not given).  Returns the directory in
    use, or ``None`` when no cache is configured.

    Call before the first jit of the process — entry points do it right
    after ``apply_platform_env()``.  Idempotent: the first configured
    directory wins (jax's cache config is process-global); a later call
    with a different directory logs and keeps the first.
    """
    global _initialized_dir
    cache_dir = cache_dir or os.environ.get(_ENV_DIR) or None
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    if _initialized_dir is not None:
        if _initialized_dir != cache_dir:
            from . import log_error

            log_error(
                "compile cache already initialized at %s; ignoring %s "
                "(jax's cache dir is process-global)",
                _initialized_dir, cache_dir,
            )
        return _initialized_dir

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    try:
        min_secs = float(os.environ.get(_ENV_MIN_SECS, "0.5"))
    except ValueError:
        min_secs = 0.5
    try:
        min_bytes = int(os.environ.get(_ENV_MIN_BYTES, "0"))
    except ValueError:
        min_bytes = 0
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", min_bytes)
    except Exception:  # noqa: BLE001 — knob absent on older jax
        pass
    _initialized_dir = cache_dir

    from . import log_info

    log_info(
        "compile cache: %s (min compile %.2fs, min entry %d B) — restarts "
        "skip recompilation of already-seen programs",
        cache_dir, min_secs, min_bytes,
    )
    return cache_dir


def compile_cache_dir() -> Optional[str]:
    """The directory ``init_compile_cache`` wired, or None."""
    return _initialized_dir
