"""Batch-size finder: measure the throughput-optimal batch for a jitted fn.

The reference ships an unused latency-model search (``src/batchsizefinder.h``,
dead code). This is the live TPU version: walk powers of two, time the jitted
function (compile excluded), stop when marginal per-sample speedup drops
below ``threshold`` or memory runs out, and return the best batch size.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from . import Timer, log_info


def find_batch_size(
    make_batch: Callable[[int], object],
    fn: Callable,
    start: int = 8,
    max_batch: int = 4096,
    threshold: float = 1.05,
    iters: int = 5,
) -> int:
    """Return the batch size with the best samples/sec.

    Args:
      make_batch: ``make_batch(n) -> args tuple`` building inputs of batch n.
      fn: jittable callable taking ``*make_batch(n)``.
      threshold: keep doubling while throughput improves by at least this
        factor; stop on regression, plateau, or OOM.
    """
    jfn = jax.jit(fn)
    best_bs, best_rate = None, 0.0
    bs = start
    while bs <= max_batch:
        try:
            args = make_batch(bs)
            out = jfn(*args)  # compile
            jax.block_until_ready(out)
            timer = Timer()
            for _ in range(iters):
                out = jfn(*args)
            jax.block_until_ready(out)
            dt = timer.elapsed() / iters
        except (RuntimeError, jax.errors.JaxRuntimeError) as e:  # OOM etc.
            log_info("batch size %d failed (%s); stopping search", bs, type(e).__name__)
            break
        rate = bs / dt
        log_info("batch %d: %.1f samples/s (%.2f ms)", bs, rate, dt * 1e3)
        if best_bs is not None and rate < best_rate * threshold:
            break
        if rate > best_rate:
            best_bs, best_rate = bs, rate
        bs *= 2
    return best_bs if best_bs is not None else start
