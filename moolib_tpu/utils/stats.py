"""Stats counters used by the example agents.

Counterpart of the reference's ``examples/common/__init__.py:9-152``:
``StatMean``/``StatSum`` accumulators whose *deltas* can be allreduced
cohort-wide (see ``GlobalStatsAccumulator`` in moolib_tpu.stats_accumulator),
and ``RunningMeanStd`` for reward normalization.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np


class StatSum:
    """A monotonically accumulating sum whose delta-since-last-reduce syncs."""

    def __init__(self, value: float = 0.0):
        self.value = float(value)

    def result(self) -> float:
        return self.value

    def __iadd__(self, other):
        self.value += float(other)
        return self

    def __isub__(self, other):
        self.value -= float(other)
        return self

    def __repr__(self):
        return f"StatSum({self.value})"

    # delta protocol used by GlobalStatsAccumulator -----------------------
    def delta(self, prev: "StatSum") -> float:
        return self.value - prev.value

    def apply_delta(self, d: float) -> None:
        self.value += d

    def snapshot(self) -> "StatSum":
        return StatSum(self.value)


class StatMean:
    """Windowed mean: (sum, count) pairs; optional exponential cutoff."""

    def __init__(self, sum_: float = 0.0, count: float = 0.0, cumulative: bool = False):
        self.sum = float(sum_)
        self.count = float(count)
        self.cumulative = cumulative

    def result(self) -> Optional[float]:
        if self.count == 0:
            return None
        return self.sum / self.count

    def reset(self) -> None:
        if not self.cumulative:
            self.sum = 0.0
            self.count = 0.0

    def __iadd__(self, other):
        if isinstance(other, StatMean):
            self.sum += other.sum
            self.count += other.count
        else:
            self.sum += float(other)
            self.count += 1
        return self

    def __repr__(self):
        return f"StatMean(sum={self.sum}, count={self.count})"

    # delta protocol -------------------------------------------------------
    def delta(self, prev: "StatMean"):
        return (self.sum - prev.sum, self.count - prev.count)

    def apply_delta(self, d) -> None:
        self.sum += d[0]
        self.count += d[1]

    def snapshot(self) -> "StatMean":
        return StatMean(self.sum, self.count, self.cumulative)


class RunningMeanStd:
    """Welford-style running mean/std over arrays (reference :138-152)."""

    def __init__(self, epsilon: float = 1e-4, shape=()):
        self.mean = np.zeros(shape, dtype=np.float64)
        self.var = np.ones(shape, dtype=np.float64)
        self.count = epsilon

    def update(self, x) -> None:
        x = np.asarray(x, dtype=np.float64)
        batch_mean = x.mean(axis=0)
        batch_var = x.var(axis=0)
        batch_count = x.shape[0]
        self._update_from_moments(batch_mean, batch_var, batch_count)

    def _update_from_moments(self, batch_mean, batch_var, batch_count) -> None:
        delta = batch_mean - self.mean
        tot = self.count + batch_count
        new_mean = self.mean + delta * batch_count / tot
        m_a = self.var * self.count
        m_b = batch_var * batch_count
        m2 = m_a + m_b + np.square(delta) * self.count * batch_count / tot
        self.mean = new_mean
        self.var = m2 / tot
        self.count = tot

    @property
    def std(self):
        return np.sqrt(self.var)


def ema(old: Optional[float], new: float, alpha: float = 0.1) -> float:
    """Exponential moving average helper."""
    if old is None or math.isnan(old):
        return new
    return (1 - alpha) * old + alpha * new
