"""Tracing/profiling hooks.

The reference has no tracer — only ``debug_info`` dumps and log timings
(SURVEY.md §5.1).  The TPU build replaces that with first-class hooks:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace of XLA execution (compile, HBM, ICI waits).
- :class:`StepTimer` — cheap wall-clock section timing with EMA summaries,
  for the python-side loop (act/learn/reduce shares).  Registry-backed:
  every section also lands in the telemetry registry
  (``loop_section_seconds{section=...}``) and records a host span, so the
  loop breakdown exports through Prometheus/Chrome-trace without the loop
  doing anything beyond ``timer.section(...)``.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` passthrough so loop
  phases show up inside device traces.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

from .. import telemetry


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (view with TensorBoard)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Label a region so it appears inside the device trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """EMA section timer for the training loop's python side.

    Each ``section`` observation additionally feeds the process telemetry:
    a ``loop_section_seconds{section=<name>}`` histogram sample in the
    registry and a span in the default tracer.  Pass ``publish=False`` (or
    a private ``registry``/``tracer``) to opt out — e.g. micro-benchmarks
    that would flood the span ring.
    """

    def __init__(
        self,
        alpha: float = 0.05,
        publish: bool = True,
        registry: Optional["telemetry.Registry"] = None,
        tracer: Optional["telemetry.Tracer"] = None,
    ):
        self._alpha = alpha
        self._ema: Dict[str, float] = {}
        self._counts: Dict[str, int] = defaultdict(int)
        self._hist = None
        self._tracer = None
        if publish:
            reg = registry or telemetry.get_registry()
            self._hist = reg.histogram(
                "loop_section_seconds", "train-loop section wall time", ("section",)
            )
            self._tracer = tracer or telemetry.get_tracer()

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        span = self._tracer.span(name) if self._tracer is not None else None
        if span is not None:
            span.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if span is not None:
                span.__exit__(None, None, None)
            if self._hist is not None:
                self._hist.observe(dt, section=name)
            prev = self._ema.get(name)
            self._ema[name] = dt if prev is None else (1 - self._alpha) * prev + self._alpha * dt
            self._counts[name] += 1

    def summary(self) -> Dict[str, float]:
        """EMA seconds per section."""
        return dict(self._ema)

    def report(self) -> str:
        total = sum(self._ema.values()) or 1e-9
        parts = [
            f"{k}={v*1e3:.1f}ms({v/total*100:.0f}%)"
            for k, v in sorted(self._ema.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)
