"""Tracing/profiling hooks.

The reference has no tracer — only ``debug_info`` dumps and log timings
(SURVEY.md §5.1).  The TPU build replaces that with first-class hooks:

- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace of XLA execution (compile, HBM, ICI waits).
- :class:`StepTimer` — cheap wall-clock section timing with EMA summaries,
  for the python-side loop (act/learn/reduce shares).
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` passthrough so loop
  phases show up inside device traces.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (view with TensorBoard)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Label a region so it appears inside the device trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """EMA section timer for the training loop's python side."""

    def __init__(self, alpha: float = 0.05):
        self._alpha = alpha
        self._ema: Dict[str, float] = {}
        self._counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            prev = self._ema.get(name)
            self._ema[name] = dt if prev is None else (1 - self._alpha) * prev + self._alpha * dt
            self._counts[name] += 1

    def summary(self) -> Dict[str, float]:
        """EMA seconds per section."""
        return dict(self._ema)

    def report(self) -> str:
        total = sum(self._ema.values()) or 1e-9
        parts = [
            f"{k}={v*1e3:.1f}ms({v/total*100:.0f}%)"
            for k, v in sorted(self._ema.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)
