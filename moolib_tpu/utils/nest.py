"""Minimal pytree ("nest") helpers over dict / list / tuple / leaf structures.

TPU-native counterpart of the reference's ``examples/common/nest.py:4-41`` and
the C++ ``utils::stackFields/unstackFields`` family (``src/batch_utils.h:21-27``).
Unlike the reference we are jax-first, so leaves are anything jax can treat as
an array (jax.Array, numpy, python scalars) and the heavy lifting is done by
``jax.tree_util`` where possible.  These helpers intentionally support only
dict/list/tuple containers — matching the wire format of the RPC layer — so a
nest serialized on one peer reassembles identically on another.
"""

from __future__ import annotations

from builtins import zip as _zip
from typing import Any, Callable, Iterator, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Nest = Any  # dict / list / tuple / leaf


def map(f: Callable, n: Nest) -> Nest:  # noqa: A001 - mirrors reference API name
    """Apply ``f`` to every leaf of ``n``, preserving structure."""
    if isinstance(n, dict):
        return {k: map(f, v) for k, v in n.items()}
    if isinstance(n, tuple):
        return tuple(map(f, v) for v in n)
    if isinstance(n, list):
        return [map(f, v) for v in n]
    return f(n)


def map_many(f: Callable, *nests: Nest) -> Nest:
    """Apply ``f`` over corresponding leaves of several same-structure nests."""
    n0 = nests[0]
    if isinstance(n0, dict):
        return {k: map_many(f, *(n[k] for n in nests)) for k in n0}
    if isinstance(n0, tuple):
        return tuple(map_many(f, *vs) for vs in _zip(*nests))
    if isinstance(n0, list):
        return [map_many(f, *vs) for vs in _zip(*nests)]
    return f(*nests)


def flatten(n: Nest) -> Iterator[Any]:
    """Yield leaves of ``n`` in deterministic (insertion/index) order."""
    if isinstance(n, dict):
        for v in n.values():
            yield from flatten(v)
    elif isinstance(n, (list, tuple)):
        for v in n:
            yield from flatten(v)
    else:
        yield n


def zip(*nests: Nest):  # noqa: A001 - mirrors reference API name
    """Zip leaves of same-structure nests into tuples (structure preserved)."""
    return map_many(lambda *xs: tuple(xs), *nests)


def pack_as(template: Nest, flat: Sequence[Any]) -> Nest:
    """Inverse of :func:`flatten` given a structure template."""
    it = iter(flat)

    def _take(_):
        return next(it)

    out = map(_take, template)
    rest = list(it)
    if rest:
        raise ValueError(f"pack_as: {len(rest)} leaves left over")
    return out


def _stack_leaves(xs, dim):
    try:
        return jnp.stack(xs, axis=dim)
    except (TypeError, ValueError):
        # Non-array leaves (strings, objects) batch as a 1-D object array —
        # still a *leaf* (lists/tuples would read as nest containers).
        out = np.empty(len(xs), dtype=object)
        for i, x in enumerate(xs):
            out[i] = x
        return out


def stack(nests: Sequence[Nest], dim: int = 0) -> Nest:
    """Stack corresponding leaves of ``nests`` along a new axis ``dim``.

    Non-array leaves are collected into lists instead (the RPC queue batching
    path sends opaque "info" objects alongside tensors).
    """
    return map_many(lambda *xs: _stack_leaves(xs, dim), *nests)


def cat(nests: Sequence[Nest], dim: int = 0) -> Nest:
    """Concatenate corresponding leaves of ``nests`` along axis ``dim``."""
    return map_many(lambda *xs: jnp.concatenate(xs, axis=dim), *nests)


def unstack(n: Nest, dim: int = 0) -> list:
    """Split every leaf along ``dim`` and return a list of nests."""
    leaves = list(flatten(n))
    if not leaves:
        return []
    first = leaves[0]
    size = np.shape(first)[0 if _is_object_array(first) else dim]
    parts = [
        map(lambda x: _index_axis(x, dim, i), n)  # noqa: B023
        for i in range(size)
    ]
    return parts


def _is_object_array(x) -> bool:
    return isinstance(x, np.ndarray) and x.dtype == object


def _index_axis(x, dim, i):
    if _is_object_array(x):  # non-array leaves batched by _stack_leaves
        return x[i]
    idx = [slice(None)] * np.ndim(x)
    idx[dim] = i
    return x[tuple(idx)]


def device_put(n: Nest, device=None, sharding=None) -> Nest:
    """Move every leaf onto a device / sharding (jax.device_put per leaf)."""
    target = sharding if sharding is not None else device
    if target is None:
        return map(jnp.asarray, n)
    return map(lambda x: jax.device_put(x, target), n)
