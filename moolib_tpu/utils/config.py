"""Hierarchical YAML config with interpolation and CLI overrides.

Counterpart of the reference's hydra/omegaconf setup
(``examples/vtrace/experiment.py:214-224``, ``examples/vtrace/config.yaml``):
YAML files, ``${section.key}`` interpolation, a ``${uid:}`` resolver for
per-run ids, and hydra-style ``key=value`` / ``section.key=value`` command
line overrides.  Implemented standalone (the image has PyYAML but not
hydra/omegaconf) and kept deliberately small.

Usage::

    cfg = Config.load("config.yaml", overrides=sys.argv[1:])
    cfg.optimizer.learning_rate   # attribute access
    cfg["optimizer"]["learning_rate"]  # mapping access
    cfg.to_dict(), cfg.to_yaml(), Config.from_dict({...})
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional

from . import create_uid

try:
    import yaml
except ImportError:  # pragma: no cover - pyyaml is in the image
    yaml = None

_INTERP = re.compile(r"\$\{([^}]*)\}")

# Registered ``${name:arg}`` resolvers (the reference registers ``uid``).
_RESOLVERS: Dict[str, Callable[[str], Any]] = {
    "uid": lambda _arg: create_uid(),
    "env": lambda name: __import__("os").environ.get(name, ""),
}


def register_resolver(name: str, fn: Callable[[str], Any]) -> None:
    _RESOLVERS[name] = fn


def _parse_scalar(text: str) -> Any:
    """Parse a CLI override value with YAML scalar rules (1 -> int, etc.)."""
    if yaml is not None:
        return yaml.safe_load(text)
    for cast in (int, float):
        try:
            return cast(text)
        except ValueError:
            pass
    if text in ("true", "True"):
        return True
    if text in ("false", "False"):
        return False
    if text in ("null", "None", "~"):
        return None
    return text


class Config:
    """A nested dict with attribute access, interpolation, and overrides.

    Child nodes remember the root config: ``${a.b}`` interpolations always
    resolve against the root (omegaconf semantics)."""

    def __init__(self, data: Optional[Dict[str, Any]] = None, _root: "Config" = None):
        object.__setattr__(self, "_data", dict(data or {}) if _root is None else data)
        object.__setattr__(self, "_root", _root if _root is not None else self)
        if _root is None:
            # Resolver calls (e.g. ${uid:}) evaluate once per config: every
            # read — and every field using the same expression — sees the
            # same value (a per-run id must not change between accesses).
            object.__setattr__(self, "_resolver_cache", {})

    # ------------------------------------------------------------- creation
    @classmethod
    def load(
        cls,
        path: Optional[str] = None,
        overrides: Optional[List[str]] = None,
        defaults: Optional[Dict[str, Any]] = None,
    ) -> "Config":
        """Build a config from (in increasing priority): ``defaults``, the
        YAML file at ``path``, then ``key=value`` overrides."""
        data: Dict[str, Any] = {}
        if defaults:
            _merge(data, defaults)
        if path is not None:
            if yaml is None:
                raise RuntimeError("pyyaml unavailable; cannot read config files")
            with open(path) as f:
                loaded = yaml.safe_load(f) or {}
            if not isinstance(loaded, dict):
                raise ValueError(f"config root must be a mapping: {path}")
            _merge(data, loaded)
        cfg = cls(data)
        for ov in overrides or []:
            cfg.apply_override(ov)
        return cfg

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Config":
        return cls(dict(data))

    # ------------------------------------------------------------- overrides
    def apply_override(self, override: str) -> None:
        """Apply one hydra-style ``a.b.c=value`` override."""
        if "=" not in override:
            raise ValueError(f"override must look like key=value: {override!r}")
        key, _, value = override.partition("=")
        node = self._data
        parts = key.strip().split(".")
        for p in parts[:-1]:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                node[p] = nxt
            node = nxt
        node[parts[-1]] = _parse_scalar(value)

    # ------------------------------------------------------------- access
    def __getattr__(self, name: str):
        if name not in self._data:
            # Only a genuinely missing key becomes AttributeError; errors
            # from resolving a *present* key (e.g. an interpolation typo)
            # must surface as-is, not be masked as a missing flag.
            raise AttributeError(name)
        return self[name]

    def __setattr__(self, name: str, value) -> None:
        self._data[name] = value

    def __getitem__(self, name: str):
        value = self._data[name]
        return self._resolve(value)

    def __setitem__(self, name: str, value) -> None:
        self._data[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self._data

    def get(self, name: str, default=None):
        try:
            return self[name]
        except KeyError:
            return default

    def keys(self):
        return self._data.keys()

    def items(self):
        return ((k, self[k]) for k in self._data)

    def __iter__(self):
        return iter(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __repr__(self) -> str:
        return f"Config({self.to_dict()!r})"

    def __eq__(self, other) -> bool:
        if isinstance(other, Config):
            return self.to_dict() == other.to_dict()
        if isinstance(other, dict):
            return self.to_dict() == other
        return NotImplemented

    # ---------------------------------------------------------- interpolation
    def _resolve(self, value, _depth: int = 0):
        if _depth > 16:
            raise ValueError("interpolation recursion limit (cycle?)")
        if isinstance(value, dict):
            return Config(value, _root=self._root)
        if isinstance(value, list):
            return [self._resolve(v, _depth + 1) for v in value]
        if isinstance(value, str):
            return self._interp(value, _depth)
        return value

    def _interp(self, text: str, depth: int):
        full = _INTERP.fullmatch(text)
        if full:  # whole-string interpolation keeps the referent's type
            return self._lookup(full.group(1), depth)
        return _INTERP.sub(lambda m: str(self._lookup(m.group(1), depth)), text)

    def _lookup(self, expr: str, depth: int):
        if ":" in expr:  # resolver call, e.g. ${uid:} or ${env:HOME}
            cache = self._root._resolver_cache
            if expr in cache:
                return cache[expr]
            name, _, arg = expr.partition(":")
            fn = _RESOLVERS.get(name)
            if fn is None:
                raise KeyError(f"no such resolver: {name!r}")
            cache[expr] = fn(arg)
            return cache[expr]
        node: Any = self._root._data
        for part in expr.split("."):
            if not isinstance(node, dict) or part not in node:
                raise KeyError(f"interpolation target not found: {expr!r}")
            node = node[part]
        return self._resolve(node, depth + 1)

    # ------------------------------------------------------------- export
    def to_dict(self) -> Dict[str, Any]:
        """Fully-resolved plain dict (interpolations applied)."""

        def conv(v):
            if isinstance(v, Config):
                return v.to_dict()
            if isinstance(v, list):
                return [conv(x) for x in v]
            return v

        return {k: conv(self[k]) for k in self._data}

    def to_yaml(self) -> str:
        if yaml is None:
            raise RuntimeError("pyyaml unavailable")
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_yaml())


def _merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    import copy

    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            # Deep-copy containers: later overrides must never mutate the
            # caller's defaults/source dicts through shared references.
            dst[k] = copy.deepcopy(v) if isinstance(v, (dict, list)) else v
