"""EnvPool: multi-process batched environment stepping over shared memory.

Counterpart of the reference's fork-server EnvPool/EnvRunner/EnvStepper
(``src/env.{h,cc}``, ``src/shm.h``, bindings ``src/moolib.cc:1587-1644``):
``num_processes`` forked worker processes each own a contiguous slice of every
batch of ``batch_size`` environments; actions are scattered through POSIX
shared memory, workers step their envs (auto-resetting on done) and write
observations/reward/done into per-batch shm slots; ``step(batch_index,
action)`` returns an ``EnvStepperFuture`` whose ``result()`` blocks on
completion semaphores and returns **zero-copy numpy views** of the shm
buffers.  ``num_batches`` > 1 gives double buffering: act on batch 0 while
batch 1 is stepping (reference ``src/moolib.cc:1587-1630`` docstring).

Design differences from the reference (TPU-first, not a translation):
- worker start method enforces the reference's fork-safety contract
  (``src/env.cc:149-169``): plain ``fork`` while the jax backend is
  uninitialized (fast, closures allowed), an automatic switch to
  ``forkserver`` afterwards (the server is fork+exec'd, so it is safe with
  jax's threads; ``create_env`` must then be picklable).  Constructing the
  pool before the first jax backend use remains the preferred order.
- the doorbell is a per-worker task queue + per-batch completion semaphore
  (futex-backed) instead of spin-waiting on atomic action words.
- results are host numpy views meant to be fed to ``Batcher``/``jax.device_put``
  which lands them in TPU HBM in one hop.

Env protocol: ``create_env()`` returns an object with ``reset() -> obs`` and
``step(action) -> (obs, reward, done, info[, truncated])`` (both gym 4-tuple
and gymnasium 5-tuple are accepted); ``obs`` is an ndarray or a flat dict of
ndarrays with fixed shapes/dtypes.
"""

from __future__ import annotations

import ctypes
import multiprocessing as mp
import os
import pickle
import sys
import traceback
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry

# Pool metrics (docs/TELEMETRY.md), parent-process side only: workers report
# through shm, and their own counters would land in a registry nobody scrapes.
_REG = telemetry.get_registry()
_M_ENV_STEPS = _REG.counter(
    "envpool_steps_total", "environment steps completed (parent-observed)"
)
_M_ENV_BATCHES = _REG.counter("envpool_batches_total", "batch steps completed")
_M_STEP_WAIT = _REG.histogram(
    "envpool_step_wait_seconds", "result() wait for a batch step to complete"
)
_M_WORKERS = _REG.gauge("envpool_workers", "worker processes of live pools")


def _jax_backend_initialized() -> bool:
    """True once any XLA backend client exists in this process — the point
    after which a plain fork() is unsafe (jax is multithreaded).  Checks
    without importing or initializing jax."""
    if sys.modules.get("jax") is None:
        return False
    try:
        from jax._src import distributed, xla_bridge

        # jax.distributed.initialize() starts gRPC/heartbeat threads before
        # any backend client exists — forking is already unsafe then.
        return bool(xla_bridge._backends) or distributed.global_state.client is not None
    except Exception:  # noqa: BLE001 — private API; fail toward the safe path
        return True

_FIELD_RESERVED = ("reward", "done")
_SHUTDOWN = -1


class _MpQueue:
    """Fallback doorbell: multiprocessing SimpleQueue of batch indices."""

    def __init__(self, ctx):
        self._q = ctx.SimpleQueue()

    def put(self, v: int) -> None:
        self._q.put(v)

    def get(self) -> int:
        return self._q.get()


class _MpSem:
    def __init__(self, ctx):
        self._s = ctx.Semaphore(0)

    def release(self) -> None:
        self._s.release()

    def acquire(self, timeout=None) -> bool:
        return self._s.acquire(True, timeout)


class _RingQueue:
    def __init__(self, ring):
        self._ring = ring

    def put(self, v: int) -> None:
        self._ring.push(int(v))

    def get(self) -> int:
        out = self._ring.pop()
        return _SHUTDOWN if out is None else out


def _doorbell_layout(lib, cap, num_processes, num_batches):
    """Single owner of the doorbell shm layout math — the parent's size
    computation and both sides' view placement must agree byte-for-byte."""
    from . import native

    ring_sz = (native.NativeRing.size(lib, cap) + 63) & ~63
    sem_sz = (native.NativeSemaphore.size(lib) + 63) & ~63
    total = ring_sz * num_processes + sem_sz * num_batches
    return ring_sz, sem_sz, total


def _native_doorbell_views(lib, buf, cap, num_processes, num_batches, initialize):
    """Construct ring/semaphore handles over a doorbell shm region."""
    from . import native

    ring_sz, sem_sz, _ = _doorbell_layout(lib, cap, num_processes, num_batches)
    base = ctypes.addressof(ctypes.c_char.from_buffer(buf))
    queues = [
        _RingQueue(
            native.NativeRing(lib, base + i * ring_sz, cap, initialize=initialize)
        )
        for i in range(num_processes)
    ]
    off = ring_sz * num_processes
    sems = [
        native.NativeSemaphore(lib, base + off + i * sem_sz, initialize=initialize)
        for i in range(num_batches)
    ]
    return queues, sems


def _make_doorbells(ctx, num_processes: int, num_batches: int):
    """Native futex rings/semaphores in one NAMED shm segment (counterpart of
    the reference's shm semaphores + queues, src/shm.h), falling back to
    multiprocessing primitives when g++ is unavailable.

    Returns ``(queues, sems, region, descriptor)``: workers reconstruct their
    handles from ``descriptor`` by attaching the named segment, so the pool
    works under both the ``fork`` and ``forkserver`` start methods (the
    anonymous-mmap design it replaces required address-space inheritance and
    thus fork)."""
    from . import native

    lib = native.get_shmq()
    if lib is None:
        queues = [_MpQueue(ctx) for _ in range(num_processes)]
        sems = [_MpSem(ctx) for _ in range(num_batches)]
        # mp primitives pickle through Process args under either start method;
        # per-worker descriptors are built at spawn so each worker receives
        # only its own queue's fds, not all N workers'.
        return queues, sems, None, ("mp", queues, sems)
    # Power-of-two capacity: the ring indexes with u32 cursors mod capacity,
    # which only stays consistent across the 2^32 wrap for powers of two.
    cap = 16
    while cap < 4 * num_batches:
        cap *= 2
    _, _, total = _doorbell_layout(lib, cap, num_processes, num_batches)
    region = shared_memory.SharedMemory(create=True, size=total)
    try:
        queues, sems = _native_doorbell_views(
            lib, region.buf, cap, num_processes, num_batches, initialize=True
        )
    except Exception:
        # Not yet owned by a pool: unlink here or the named segment leaks.
        try:
            region.unlink()
        except Exception:
            pass
        raise
    return queues, sems, region, ("native", region.name, cap, num_processes, num_batches)


def _worker_doorbell_desc(desc, worker_index):
    """Slice the pool-wide descriptor down to one worker's share (mp fallback:
    just that worker's queue, so its peers' pipe fds never travel)."""
    if desc[0] == "mp":
        _, queues, sems = desc
        return ("mp", queues[worker_index], sems)
    return desc


def _attach_doorbells(desc, worker_index):
    """Worker-side counterpart of :func:`_make_doorbells`: resolve the
    descriptor into (task_queue, done_sems[, segment])."""
    if desc[0] == "mp":
        _, queue, sems = desc
        return queue, sems, None
    from . import native

    _, shm_name, cap, num_processes, num_batches = desc
    seg = shared_memory.SharedMemory(name=shm_name)
    queues, sems = _native_doorbell_views(
        native.get_shmq(), seg.buf, cap, num_processes, num_batches, initialize=False
    )
    return queues[worker_index], sems, seg


def _normalize_obs(obs) -> Dict[str, np.ndarray]:
    if isinstance(obs, dict):
        return {k: np.asarray(v) for k, v in obs.items()}
    return {"state": np.asarray(obs)}


def _step_env(env, action):
    """Step with auto-reset; tolerate gym (4-tuple) and gymnasium (5-tuple)."""
    out = env.step(action)
    if len(out) == 5:
        obs, reward, terminated, truncated, _info = out
        done = bool(terminated) or bool(truncated)
    else:
        obs, reward, done, _info = out
        done = bool(done)
    if done:
        obs = env.reset()
        if isinstance(obs, tuple):  # gymnasium reset -> (obs, info)
            obs = obs[0]
    return obs, float(reward), done


def _reset_env(env):
    obs = env.reset()
    if isinstance(obs, tuple):
        obs = obs[0]
    return obs


class EnvRunner:
    """Worker-process loop: owns envs [lo, hi) of every batch (reference
    ``EnvRunner::run`` ``src/env.h:407-453``)."""

    def __init__(self, create_env, worker_index, lo, hi, num_batches, conn,
                 task_queue, done_sems, discover: bool = False):
        self.create_env = create_env
        self.worker_index = worker_index
        self.lo = lo
        self.hi = hi
        self.num_batches = num_batches
        self.conn = conn
        self.task_queue = task_queue
        self.done_sems = done_sems
        self.discover = discover
        self.envs: Dict[Tuple[int, int], Any] = {}
        self._running = False

    def start(self) -> None:
        self._running = True
        self.run()

    def running(self) -> bool:
        return self._running

    def run(self) -> None:
        if self.discover:
            # Spec discovery happens in THIS worker's first real env: the shm
            # batch layout derives from its reset observation (reference
            # allocateBatch-from-first-obs, ``src/env.h:214-246``) and the
            # env is kept for stepping — no throwaway probe process.
            try:
                env = self.create_env()
                obs = _normalize_obs(_reset_env(env))
                spec = {k: (v.shape, v.dtype.str) for k, v in obs.items()}
                self.conn.send(("ok", spec))
                if self.lo < self.hi and self.num_batches > 0:
                    self.envs[(0, self.lo)] = env  # freshly reset; first
                    # step() on this slot steps it like the lazy path would
            except Exception as e:  # noqa: BLE001 — parent raises it
                try:
                    self.conn.send(("error", repr(e)))
                except Exception:
                    pass
                return
        # Wait for the parent to send the shm layout (created after spec
        # discovery), then serve step requests until shutdown.
        try:
            layout = self.conn.recv()
        except EOFError:
            return
        obs_shm = {}
        views: Dict[int, Dict[str, np.ndarray]] = {}
        act_views: Dict[int, np.ndarray] = {}
        segs = []
        for b in range(self.num_batches):
            views[b] = {}
            for key, (shm_name, shape, dtype) in layout["obs"][b].items():
                seg = shared_memory.SharedMemory(name=shm_name)
                segs.append(seg)
                views[b][key] = np.ndarray(shape, dtype=dtype, buffer=seg.buf)
            shm_name, shape, dtype = layout["act"][b]
            seg = shared_memory.SharedMemory(name=shm_name)
            segs.append(seg)
            act_views[b] = np.ndarray(shape, dtype=dtype, buffer=seg.buf)
        try:
            while True:
                b = self._get_task()
                if b is None or b == _SHUTDOWN:
                    break
                try:
                    self._step_batch(b, views[b], act_views[b])
                except Exception:
                    # Report the env traceback to the parent (result() polls
                    # the pipe) before dying — a user env bug surfaces in
                    # seconds with its real traceback, not as an opaque
                    # 120 s step timeout.
                    try:
                        self.conn.send(
                            ("step_error", self.worker_index, traceback.format_exc())
                        )
                    except Exception:
                        pass
                    raise
                self.done_sems[b].release()
        finally:
            for seg in segs:
                seg.close()

    def _get_task(self):
        """Blocking task fetch with an idle suicide timer: an orphaned worker
        (parent gone without close()) exits instead of lingering forever
        (reference EnvRunner 1800 s idle suicide, src/env.h:446-450)."""
        get = getattr(self.task_queue, "get_timeout", None)
        if get is None and hasattr(self.task_queue, "_ring"):
            out = self.task_queue._ring.pop(timeout=1800.0)
            return _SHUTDOWN if out is None else out
        return self.task_queue.get()

    def _step_batch(self, b: int, view: Dict[str, np.ndarray], actions: np.ndarray):
        for i in range(self.lo, self.hi):
            env = self.envs.get((b, i))
            if env is None:
                env = self.create_env()
                self.envs[(b, i)] = env
                obs = _normalize_obs(_reset_env(env))
                reward, done = 0.0, False
                # Apply the incoming action to the fresh env.
                obs_, reward, done = _step_env(env, actions[i])
                obs = _normalize_obs(obs_)
            else:
                obs_, reward, done = _step_env(env, actions[i])
                obs = _normalize_obs(obs_)
            for k, v in obs.items():
                view[k][i] = v
            view["reward"][i] = reward
            view["done"][i] = done


def _worker_main(create_env, worker_index, lo, hi, num_batches, conn, doorbells,
                 discover=False):
    task_queue, done_sems, seg = _attach_doorbells(doorbells, worker_index)
    runner = EnvRunner(
        create_env, worker_index, lo, hi, num_batches, conn, task_queue,
        done_sems, discover=discover,
    )
    try:
        runner.start()
    finally:
        if seg is not None:
            seg.close()


class EnvStepperFuture:
    """Future for one in-flight batch step (reference ``EnvStepperFuture``)."""

    def __init__(self, stepper: "EnvStepper", batch_index: int):
        self._stepper = stepper
        self._batch_index = batch_index
        self._done = False

    def result(self) -> Dict[str, np.ndarray]:
        """Wait for every worker, then return zero-copy shm views."""
        if self._done:
            return self._stepper._views[self._batch_index]
        s = self._stepper
        import time as _time

        t0 = _time.monotonic()
        deadline = t0 + s._timeout
        acquired = 0
        while acquired < s._num_workers:
            if s._done_sems[self._batch_index].acquire(timeout=0.5):
                acquired += 1
                continue
            # Slow path: while waiting, surface worker failures promptly
            # with the env's real traceback instead of a blind timeout.
            s._pool._check_workers()
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"EnvPool step batch {self._batch_index} timed out "
                    f"({s._timeout}s); an env worker may have died"
                )
        _M_STEP_WAIT.observe(_time.monotonic() - t0)
        _M_ENV_BATCHES.inc()
        _M_ENV_STEPS.inc(s._pool._batch_size)
        self._done = True
        s._inflight[self._batch_index] = None
        return s._views[self._batch_index]


class EnvStepper:
    """Scatters actions and wakes workers (reference ``EnvStepper::step``
    ``src/env.cc:273-349``)."""

    def __init__(self, pool: "EnvPool"):
        self._pool = pool
        self._num_workers = pool._num_processes
        self._timeout = 120.0
        self._views = pool._obs_views
        self._act_views = pool._act_views
        self._done_sems = pool._done_sems
        self._task_queues = pool._task_queues
        self._inflight: List[Optional[EnvStepperFuture]] = [None] * pool._num_batches

    def step(self, batch_index: int, action) -> EnvStepperFuture:
        if self._inflight[batch_index] is not None:
            raise RuntimeError(
                f"batch {batch_index} already has a step in flight; call result() first"
            )
        act = np.asarray(action)
        av = self._act_views[batch_index]
        if act.shape != av.shape:
            act = act.reshape(av.shape)
        av[...] = act
        fut = EnvStepperFuture(self, batch_index)
        self._inflight[batch_index] = fut
        for q in self._task_queues:
            q.put(batch_index)
        return fut


class EnvPool:
    """User-facing pool (reference ctor args: create_env, num_processes,
    batch_size, num_batches — ``src/moolib.cc:1614-1615``)."""

    def __init__(
        self,
        create_env: Callable[[], Any],
        num_processes: int,
        batch_size: int,
        num_batches: int = 1,
        action_dtype=np.int64,
        action_shape: Tuple[int, ...] = (),
    ):
        if num_processes < 1 or batch_size < 1 or num_batches < 1:
            raise ValueError("num_processes, batch_size, num_batches must be >= 1")
        num_processes = min(num_processes, batch_size)
        self._num_processes = num_processes
        self._batch_size = batch_size
        self._num_batches = num_batches
        # Set teardown state first: a ctor failure after shm allocation must
        # reach close() (named segments outlive the process if never
        # unlinked, unlike the anonymous mappings they replaced).
        self._closed = False
        self._segments = []
        self._doorbell_region = None
        self._task_queues: List = []
        self._procs: List = []
        self._worker_conns: List = []
        try:
            self._build(
                create_env, num_processes, batch_size, num_batches,
                action_dtype, action_shape,
            )
        except Exception:
            self.close()  # unlink any shm already allocated
            raise

    def _build(
        self, create_env, num_processes, batch_size, num_batches,
        action_dtype, action_shape,
    ):
        # Start-method contract (reference fork guard src/env.cc:149-169): a
        # plain fork() after the jax backend has started its threads is a
        # deadlock lottery, so fork is only chosen while jax is uninitialized.
        # Afterwards workers come from a forkserver — the server process is
        # launched via fork+exec (thread-safe) and its children are clean —
        # at the cost of create_env needing to be picklable.
        start = os.environ.get("MOOLIB_TPU_ENVPOOL_START")
        if start is None:
            start = "fork" if not _jax_backend_initialized() else "forkserver"
        if start == "forkserver":
            try:
                pickle.dumps(create_env)
            except Exception as e:
                raise RuntimeError(
                    "EnvPool after jax initialization uses the forkserver start "
                    f"method, which requires a picklable create_env ({e!r}). "
                    "Either construct the EnvPool before the first jax backend "
                    "use (preferred; the reference forks early for the same "
                    "reason), or pass a module-level function / functools."
                    "partial instead of a closure."
                ) from e
        ctx = mp.get_context(start)

        # 1. Spawn worker 0 first: it discovers the observation spec from its
        # own first env (which it keeps and steps) — the shm layout derives
        # from a real first observation, reference ``src/env.h:214-246``.
        self._task_queues, self._done_sems, self._doorbell_region, doorbell_desc = (
            _make_doorbells(ctx, num_processes, num_batches)
        )
        per = batch_size // num_processes
        extra = batch_size % num_processes
        bounds = []
        lo = 0
        for w in range(num_processes):
            hi = lo + per + (1 if w < extra else 0)
            bounds.append((lo, hi))
            lo = hi

        def spawn(w, discover=False):
            pconn, cconn = ctx.Pipe()
            p = ctx.Process(
                target=_worker_main,
                args=(
                    create_env,
                    w,
                    bounds[w][0],
                    bounds[w][1],
                    num_batches,
                    cconn,
                    _worker_doorbell_desc(doorbell_desc, w),
                    discover,
                ),
                daemon=True,
            )
            p.start()
            return p, pconn

        p0, p0conn = spawn(0, discover=True)
        self._procs = [p0]
        self._worker_conns = [p0conn]
        if not p0conn.poll(60):
            raise RuntimeError("EnvPool: env spec discovery timed out")
        status, spec = p0conn.recv()
        if status != "ok":
            raise RuntimeError(f"EnvPool: create_env failed in worker 0: {spec}")
        for key in _FIELD_RESERVED:
            if key in spec:
                raise ValueError(f"observation key {key!r} is reserved")

        # 2. Allocate shared memory: per batch, [batch_size, *obs_shape] per
        # key + reward/done + actions.
        self._segments: List[shared_memory.SharedMemory] = []
        self._obs_views: List[Dict[str, np.ndarray]] = []
        self._act_views: List[np.ndarray] = []
        layout_obs, layout_act = [], []
        full_spec = dict(spec)
        full_spec["reward"] = ((), "<f4")
        full_spec["done"] = ((), "|b1")
        for b in range(num_batches):
            views, meta = {}, {}
            for key, (shape, dtype) in full_spec.items():
                arr_shape = (batch_size, *shape)
                nbytes = int(np.prod(arr_shape, dtype=np.int64)) * np.dtype(dtype).itemsize
                seg = shared_memory.SharedMemory(create=True, size=max(nbytes, 1))
                self._segments.append(seg)
                views[key] = np.ndarray(arr_shape, dtype=dtype, buffer=seg.buf)
                views[key].fill(0)
                meta[key] = (seg.name, arr_shape, dtype)
            self._obs_views.append(views)
            layout_obs.append(meta)
            act_shape = (batch_size, *action_shape)
            seg = shared_memory.SharedMemory(
                create=True, size=int(np.prod(act_shape, dtype=np.int64) or 1) * np.dtype(action_dtype).itemsize
            )
            self._segments.append(seg)
            av = np.ndarray(act_shape, dtype=action_dtype, buffer=seg.buf)
            av.fill(0)
            self._act_views.append(av)
            layout_act.append((seg.name, act_shape, np.dtype(action_dtype).str))

        # 3. Ship the layout to worker 0 and spawn the rest with it.
        layout = {"obs": layout_obs, "act": layout_act}
        p0conn.send(layout)
        for w in range(1, num_processes):
            p, pconn = spawn(w)
            pconn.send(layout)
            self._procs.append(p)
            self._worker_conns.append(pconn)
        self._stepper = EnvStepper(self)
        _M_WORKERS.inc(num_processes)

    def _check_workers(self) -> None:
        """Raise if a worker reported an env exception or died."""
        for i, (p, conn) in enumerate(zip(self._procs, self._worker_conns)):
            try:
                while conn.poll():
                    msg = conn.recv()
                    if isinstance(msg, tuple) and msg and msg[0] == "step_error":
                        raise RuntimeError(
                            f"EnvPool worker {msg[1]} env exception:\n{msg[2]}"
                        )
            except (EOFError, OSError):
                pass
            if not p.is_alive():
                raise RuntimeError(
                    f"EnvPool worker {i} died (exit code {p.exitcode})"
                )

    def step(self, batch_index: int, action) -> EnvStepperFuture:
        if not 0 <= batch_index < self._num_batches:
            raise ValueError(f"batch_index {batch_index} out of range")
        return self._stepper.step(batch_index, action)

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def num_batches(self) -> int:
        return self._num_batches

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if getattr(self, "_stepper", None) is not None:
            # The gauge only counted fully-built pools (_build's last line).
            _M_WORKERS.dec(self._num_processes)
        for q in self._task_queues:
            try:
                q.put(_SHUTDOWN)
            except Exception:
                pass
        # Close the pipes first: a worker still blocked in its layout recv
        # (ctor failed between spec discovery and layout send) wakes with
        # EOFError and exits instead of eating the 5 s join timeout.
        for conn in self._worker_conns:
            try:
                conn.close()
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for seg in self._segments:
            try:
                seg.close()
                seg.unlink()
            except Exception:
                pass
        if self._doorbell_region is not None:
            try:
                self._doorbell_region.unlink()
            except Exception:
                pass

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
