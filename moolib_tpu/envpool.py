"""EnvPool: multi-process batched environment stepping over shared memory.

Counterpart of the reference's fork-server EnvPool/EnvRunner/EnvStepper
(``src/env.{h,cc}``, ``src/shm.h``, bindings ``src/moolib.cc:1587-1644``):
``num_processes`` forked worker processes each own a contiguous slice of every
batch of ``batch_size`` environments; actions are scattered through POSIX
shared memory, workers step their envs (auto-resetting on done) and write
observations/reward/done into per-batch shm slots; ``step(batch_index,
action)`` returns an ``EnvStepperFuture`` whose ``result()`` blocks on
completion semaphores and returns **zero-copy numpy views** of the shm
buffers.  ``num_batches`` > 1 gives double buffering: act on batch 0 while
batch 1 is stepping (reference ``src/moolib.cc:1587-1630`` docstring).

Design differences from the reference (TPU-first, not a translation):
- worker start method enforces the reference's fork-safety contract
  (``src/env.cc:149-169``): plain ``fork`` while the jax backend is
  uninitialized (fast, closures allowed), an automatic switch to
  ``forkserver`` afterwards (the server is fork+exec'd, so it is safe with
  jax's threads; ``create_env`` must then be picklable).  Constructing the
  pool before the first jax backend use remains the preferred order.
- the doorbell is a per-worker task queue + per-batch completion semaphore
  (futex-backed) instead of spin-waiting on atomic action words.
- results are host numpy views meant to be fed to ``Batcher``/``jax.device_put``
  which lands them in TPU HBM in one hop.
- worker death is a supervised event, not a run-killer: a slot that dies is
  respawned and re-attached to the existing shm segments/doorbells, its
  in-flight step tasks are re-issued (pending ``EnvStepperFuture``s complete
  through a shm progress ledger), and only a slot exceeding its
  :class:`RestartPolicy` respawn budget surfaces a hard error
  (docs/RESILIENCE.md; ``envpool_worker_restarts`` /
  ``envpool_worker_quarantined`` telemetry counters).

Env protocol: ``create_env()`` returns an object with ``reset() -> obs`` and
``step(action) -> (obs, reward, done, info[, truncated])`` (both gym 4-tuple
and gymnasium 5-tuple are accepted); ``obs`` is an ndarray or a flat dict of
ndarrays with fixed shapes/dtypes.
"""

from __future__ import annotations

import ctypes
import dataclasses
import multiprocessing as mp
import os
import pickle
import sys
import time
import traceback
from collections import deque
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry, utils

# Pool metrics (docs/TELEMETRY.md), parent-process side only: workers report
# through shm, and their own counters would land in a registry nobody scrapes.
_REG = telemetry.get_registry()
_M_ENV_STEPS = _REG.counter(
    "envpool_steps_total", "environment steps completed (parent-observed)"
)
_M_ENV_BATCHES = _REG.counter("envpool_batches_total", "batch steps completed")
_M_STEP_WAIT = _REG.histogram(
    "envpool_step_wait_seconds", "result() wait for a batch step to complete"
)
_M_WORKERS = _REG.gauge("envpool_workers", "worker processes of live pools")
_M_RESTARTS = _REG.counter(
    "envpool_worker_restarts", "worker processes respawned after an unexpected death"
)
_M_QUARANTINED = _REG.counter(
    "envpool_worker_quarantined", "worker slots hard-failed after repeated deaths"
)


@dataclasses.dataclass
class RestartPolicy:
    """Supervision policy for EnvPool worker processes (docs/RESILIENCE.md).

    A worker that dies without reporting an env exception is respawned and
    re-attached to the pool's existing shm segments and doorbells; in-flight
    step tasks it never completed are re-issued so pending futures still
    complete.  A slot that dies more than ``max_restarts`` times within
    ``window`` seconds is quarantined: the death surfaces as a hard
    ``RuntimeError`` (crash loops must not spin silently).  ``enabled=False``
    (or ``max_restarts=0``) restores the fail-fast behavior: any worker
    death raises immediately.
    """

    max_restarts: int = 3
    window: float = 60.0
    enabled: bool = True


def _jax_backend_initialized() -> bool:
    """True once any XLA backend client exists in this process — the point
    after which a plain fork() is unsafe (jax is multithreaded).  Checks
    without importing or initializing jax."""
    if sys.modules.get("jax") is None:
        return False
    try:
        from jax._src import distributed, xla_bridge

        # jax.distributed.initialize() starts gRPC/heartbeat threads before
        # any backend client exists — forking is already unsafe then.
        return bool(xla_bridge._backends) or distributed.global_state.client is not None
    except Exception:  # noqa: BLE001 — private API; fail toward the safe path
        return True

_FIELD_RESERVED = ("reward", "done")
_SHUTDOWN = -1


class _MpQueue:
    """Fallback doorbell: multiprocessing SimpleQueue of batch indices."""

    def __init__(self, ctx):
        self._q = ctx.SimpleQueue()

    def put(self, v: int) -> None:
        self._q.put(v)

    def get(self) -> int:
        return self._q.get()

    def drain(self) -> None:
        """Discard queued tasks.  Only safe while the consumer is dead and
        the caller is the sole producer (worker-respawn recovery)."""
        while not self._q.empty():
            self._q.get()


class _MpSem:
    def __init__(self, ctx):
        self._s = ctx.Semaphore(0)

    def release(self) -> None:
        self._s.release()

    def acquire(self, timeout=None) -> bool:
        return self._s.acquire(True, timeout)


class _RingQueue:
    def __init__(self, ring):
        self._ring = ring

    def put(self, v: int) -> None:
        self._ring.push(int(v))

    def get(self) -> int:
        out = self._ring.pop()
        return _SHUTDOWN if out is None else out

    def drain(self) -> None:
        """Discard queued tasks (worker-respawn recovery; see _MpQueue)."""
        while self._ring.pop(timeout=0) is not None:
            pass


def _doorbell_layout(lib, cap, num_processes, num_batches):
    """Single owner of the doorbell shm layout math — the parent's size
    computation and both sides' view placement must agree byte-for-byte."""
    from . import native

    ring_sz = (native.NativeRing.size(lib, cap) + 63) & ~63
    sem_sz = (native.NativeSemaphore.size(lib) + 63) & ~63
    total = ring_sz * num_processes + sem_sz * num_batches
    return ring_sz, sem_sz, total


def _native_doorbell_views(lib, buf, cap, num_processes, num_batches, initialize):
    """Construct ring/semaphore handles over a doorbell shm region."""
    from . import native

    ring_sz, sem_sz, _ = _doorbell_layout(lib, cap, num_processes, num_batches)
    base = ctypes.addressof(ctypes.c_char.from_buffer(buf))
    queues = [
        _RingQueue(
            native.NativeRing(lib, base + i * ring_sz, cap, initialize=initialize)
        )
        for i in range(num_processes)
    ]
    off = ring_sz * num_processes
    sems = [
        native.NativeSemaphore(lib, base + off + i * sem_sz, initialize=initialize)
        for i in range(num_batches)
    ]
    return queues, sems


def _make_doorbells(ctx, num_processes: int, num_batches: int):
    """Native futex rings/semaphores in one NAMED shm segment (counterpart of
    the reference's shm semaphores + queues, src/shm.h), falling back to
    multiprocessing primitives when g++ is unavailable.

    Returns ``(queues, sems, region, descriptor)``: workers reconstruct their
    handles from ``descriptor`` by attaching the named segment, so the pool
    works under both the ``fork`` and ``forkserver`` start methods (the
    anonymous-mmap design it replaces required address-space inheritance and
    thus fork)."""
    from . import native

    lib = native.get_shmq()
    if lib is None:
        queues = [_MpQueue(ctx) for _ in range(num_processes)]
        sems = [_MpSem(ctx) for _ in range(num_batches)]
        # mp primitives pickle through Process args under either start method;
        # per-worker descriptors are built at spawn so each worker receives
        # only its own queue's fds, not all N workers'.
        return queues, sems, None, ("mp", queues, sems)
    # Power-of-two capacity: the ring indexes with u32 cursors mod capacity,
    # which only stays consistent across the 2^32 wrap for powers of two.
    cap = 16
    while cap < 4 * num_batches:
        cap *= 2
    _, _, total = _doorbell_layout(lib, cap, num_processes, num_batches)
    region = shared_memory.SharedMemory(create=True, size=total)
    try:
        queues, sems = _native_doorbell_views(
            lib, region.buf, cap, num_processes, num_batches, initialize=True
        )
    except Exception:
        # Not yet owned by a pool: unlink here or the named segment leaks.
        try:
            region.unlink()
        except Exception:
            pass
        raise
    return queues, sems, region, ("native", region.name, cap, num_processes, num_batches)


def _worker_doorbell_desc(desc, worker_index):
    """Slice the pool-wide descriptor down to one worker's share (mp fallback:
    just that worker's queue, so its peers' pipe fds never travel)."""
    if desc[0] == "mp":
        _, queues, sems = desc
        return ("mp", queues[worker_index], sems)
    return desc


def _attach_doorbells(desc, worker_index):
    """Worker-side counterpart of :func:`_make_doorbells`: resolve the
    descriptor into (task_queue, done_sems[, segment])."""
    if desc[0] == "mp":
        _, queue, sems = desc
        return queue, sems, None
    from . import native

    _, shm_name, cap, num_processes, num_batches = desc
    seg = shared_memory.SharedMemory(name=shm_name)
    queues, sems = _native_doorbell_views(
        native.get_shmq(), seg.buf, cap, num_processes, num_batches, initialize=False
    )
    return queues[worker_index], sems, seg


def _normalize_obs(obs) -> Dict[str, np.ndarray]:
    if isinstance(obs, dict):
        return {k: np.asarray(v) for k, v in obs.items()}
    return {"state": np.asarray(obs)}


def _step_env(env, action):
    """Step with auto-reset; tolerate gym (4-tuple) and gymnasium (5-tuple)."""
    out = env.step(action)
    if len(out) == 5:
        obs, reward, terminated, truncated, _info = out
        done = bool(terminated) or bool(truncated)
    else:
        obs, reward, done, _info = out
        done = bool(done)
    if done:
        obs = env.reset()
        if isinstance(obs, tuple):  # gymnasium reset -> (obs, info)
            obs = obs[0]
    return obs, float(reward), done


def _reset_env(env):
    obs = env.reset()
    if isinstance(obs, tuple):
        obs = obs[0]
    return obs


class EnvRunner:
    """Worker-process loop: owns envs [lo, hi) of every batch (reference
    ``EnvRunner::run`` ``src/env.h:407-453``)."""

    def __init__(self, create_env, worker_index, lo, hi, num_batches, conn,
                 task_queue, done_sems, discover: bool = False):
        self.create_env = create_env
        self.worker_index = worker_index
        self.lo = lo
        self.hi = hi
        self.num_batches = num_batches
        self.conn = conn
        self.task_queue = task_queue
        self.done_sems = done_sems
        self.discover = discover
        self.envs: Dict[Tuple[int, int], Any] = {}
        self._running = False

    def start(self) -> None:
        self._running = True
        self.run()

    def running(self) -> bool:
        return self._running

    def run(self) -> None:
        if self.discover:
            # Spec discovery happens in THIS worker's first real env: the shm
            # batch layout derives from its reset observation (reference
            # allocateBatch-from-first-obs, ``src/env.h:214-246``) and the
            # env is kept for stepping — no throwaway probe process.
            try:
                env = self.create_env()
                obs = _normalize_obs(_reset_env(env))
                spec = {k: (v.shape, v.dtype.str) for k, v in obs.items()}
                self.conn.send(("ok", spec))
                if self.lo < self.hi and self.num_batches > 0:
                    self.envs[(0, self.lo)] = env  # freshly reset; first
                    # step() on this slot steps it like the lazy path would
            except Exception as e:  # noqa: BLE001 — parent raises it
                try:
                    self.conn.send(("error", repr(e)))
                except Exception:
                    pass
                return
        # Wait for the parent to send the shm layout (created after spec
        # discovery), then serve step requests until shutdown.
        try:
            layout = self.conn.recv()
        except EOFError:
            return
        obs_shm = {}
        views: Dict[int, Dict[str, np.ndarray]] = {}
        act_views: Dict[int, np.ndarray] = {}
        segs = []
        for b in range(self.num_batches):
            views[b] = {}
            for key, (shm_name, shape, dtype) in layout["obs"][b].items():
                seg = shared_memory.SharedMemory(name=shm_name)
                segs.append(seg)
                views[b][key] = np.ndarray(shape, dtype=dtype, buffer=seg.buf)
            shm_name, shape, dtype = layout["act"][b]
            seg = shared_memory.SharedMemory(name=shm_name)
            segs.append(seg)
            act_views[b] = np.ndarray(shape, dtype=dtype, buffer=seg.buf)
        # Completion ledger [num_batches, num_processes]: cell (b, w) counts
        # the batch-b steps THIS worker finished.  Written after the slice
        # lands, so the parent can always tell a completed slice from one a
        # killed worker left half-written — the recovery ground truth (the
        # per-batch semaphore is only a wake-up hint).
        progress = None
        if "progress" in layout:
            shm_name, shape, dtype = layout["progress"]
            seg = shared_memory.SharedMemory(name=shm_name)
            segs.append(seg)
            progress = np.ndarray(shape, dtype=dtype, buffer=seg.buf)
        try:
            while True:
                b = self._get_task()
                if b is None or b == _SHUTDOWN:
                    break
                try:
                    self._step_batch(b, views[b], act_views[b])
                except Exception:
                    # Report the env traceback to the parent (result() polls
                    # the pipe) before dying — a user env bug surfaces in
                    # seconds with its real traceback, not as an opaque
                    # 120 s step timeout.
                    try:
                        self.conn.send(
                            ("step_error", self.worker_index, traceback.format_exc())
                        )
                    except Exception:
                        pass
                    raise
                if progress is not None:
                    progress[b, self.worker_index] += 1
                self.done_sems[b].release()
        finally:
            for seg in segs:
                seg.close()

    def _get_task(self):
        """Blocking task fetch with an idle suicide timer: an orphaned worker
        (parent gone without close()) exits instead of lingering forever
        (reference EnvRunner 1800 s idle suicide, src/env.h:446-450)."""
        get = getattr(self.task_queue, "get_timeout", None)
        if get is None and hasattr(self.task_queue, "_ring"):
            out = self.task_queue._ring.pop(timeout=1800.0)
            return _SHUTDOWN if out is None else out
        return self.task_queue.get()

    def _step_batch(self, b: int, view: Dict[str, np.ndarray], actions: np.ndarray):
        for i in range(self.lo, self.hi):
            env = self.envs.get((b, i))
            if env is None:
                env = self.create_env()
                # create_env may have pulled in jax (forkserver workers
                # start jax-free): wire the compile cache before the env's
                # first real step compiles anything.
                _maybe_init_worker_compile_cache()
                self.envs[(b, i)] = env
                obs = _normalize_obs(_reset_env(env))
                reward, done = 0.0, False
                # Apply the incoming action to the fresh env.
                obs_, reward, done = _step_env(env, actions[i])
                obs = _normalize_obs(obs_)
            else:
                obs_, reward, done = _step_env(env, actions[i])
                obs = _normalize_obs(obs_)
            for k, v in obs.items():
                view[k][i] = v
            view["reward"][i] = reward
            view["done"][i] = done


def _maybe_init_worker_compile_cache() -> None:
    """Persistent compile cache for jax-USING envs: a respawned worker
    skips recompilation exactly like a restarted peer.  Strictly gated on
    jax already being loaded in this worker (fork start inherits it; a
    forkserver worker only loads it if create_env does): the common
    jax-free env must never pay a jax import for a cache it cannot use."""
    if "jax" in sys.modules:
        utils.init_compile_cache()


def _worker_main(create_env, worker_index, lo, hi, num_batches, conn, doorbells,
                 discover=False):
    _maybe_init_worker_compile_cache()
    task_queue, done_sems, seg = _attach_doorbells(doorbells, worker_index)
    runner = EnvRunner(
        create_env, worker_index, lo, hi, num_batches, conn, task_queue,
        done_sems, discover=discover,
    )
    try:
        runner.start()
    finally:
        if seg is not None:
            seg.close()


class EnvStepperFuture:
    """Future for one in-flight batch step (reference ``EnvStepperFuture``)."""

    def __init__(self, stepper: "EnvStepper", batch_index: int):
        self._stepper = stepper
        self._batch_index = batch_index
        self._done = False

    def result(self) -> Dict[str, np.ndarray]:
        """Wait for every worker, then return zero-copy shm views.

        Completion is judged from the shm progress ledger (each worker's
        per-batch step count reaching the pool's issued count) rather than
        by counting semaphore permits: the semaphore is just a wake-up
        hint, so a worker killed mid-step and respawned by the supervisor
        (``RestartPolicy``) completes this same future once its re-issued
        slice lands — no permit bookkeeping can go stale.  On timeout or a
        hard worker failure the in-flight slot is cleared and the
        semaphore drained before the error propagates, so the next
        ``step()`` on this batch (and teardown) cannot wedge on the
        leftovers of a failed one.
        """
        if self._done:
            return self._stepper._views[self._batch_index]
        s = self._stepper
        pool = s._pool
        b = self._batch_index
        t0 = time.monotonic()
        deadline = t0 + s._timeout
        sem = s._done_sems[b]
        try:
            while not pool._batch_complete(b):
                if sem.acquire(timeout=0.25):
                    continue
                # Slow path: surface env exceptions promptly with their real
                # traceback, and respawn/quarantine dead workers per the
                # restart policy (a respawn re-issues this batch's task, so
                # the loop then completes via the progress ledger).
                pool._check_workers()
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"EnvPool step batch {b} timed out "
                        f"({s._timeout}s); an env worker may be wedged"
                    )
        except BaseException:
            pool._abort_batch(b)
            raise
        while sem.acquire(timeout=0):  # drop leftover wake-up hints
            pass
        _M_STEP_WAIT.observe(time.monotonic() - t0)
        _M_ENV_BATCHES.inc()
        _M_ENV_STEPS.inc(pool._batch_size)
        self._done = True
        s._inflight[b] = None
        return s._views[b]


class EnvStepper:
    """Scatters actions and wakes workers (reference ``EnvStepper::step``
    ``src/env.cc:273-349``)."""

    def __init__(self, pool: "EnvPool"):
        self._pool = pool
        self._num_workers = pool._num_processes
        self._timeout = 120.0
        self._views = pool._obs_views
        self._act_views = pool._act_views
        self._done_sems = pool._done_sems
        self._task_queues = pool._task_queues
        self._inflight: List[Optional[EnvStepperFuture]] = [None] * pool._num_batches

    def step(self, batch_index: int, action) -> EnvStepperFuture:
        if self._inflight[batch_index] is not None:
            raise RuntimeError(
                f"batch {batch_index} already has a step in flight; call result() first"
            )
        # Device/async action seam (docs/DESIGN.md "Actor data plane"): a
        # jax.Array (or rollout.PendingAction) is accepted directly — its
        # D2H is started async so the blocking np.asarray below completes
        # from a transfer that overlapped the caller's dispatch work rather
        # than starting one now.
        if hasattr(action, "copy_to_host_async"):
            action.copy_to_host_async()
        act = np.asarray(action)
        av = self._act_views[batch_index]
        if act.shape != av.shape:
            act = act.reshape(av.shape)
        av[...] = act
        fut = EnvStepperFuture(self, batch_index)
        self._inflight[batch_index] = fut
        # Bump the issued-step count BEFORE ringing any doorbell: a worker's
        # progress cell must never be observed ahead of the target.
        self._pool._targets[batch_index] += 1
        for q in self._task_queues:
            q.put(batch_index)
        return fut


class EnvPool:
    """User-facing pool (reference ctor args: create_env, num_processes,
    batch_size, num_batches — ``src/moolib.cc:1614-1615``), plus
    ``restart_policy`` governing worker-death supervision
    (:class:`RestartPolicy`; pass ``RestartPolicy(enabled=False)`` for the
    fail-fast behavior)."""

    def __init__(
        self,
        create_env: Callable[[], Any],
        num_processes: int,
        batch_size: int,
        num_batches: int = 1,
        action_dtype=np.int64,
        action_shape: Tuple[int, ...] = (),
        restart_policy: Optional[RestartPolicy] = None,
    ):
        if num_processes < 1 or batch_size < 1 or num_batches < 1:
            raise ValueError("num_processes, batch_size, num_batches must be >= 1")
        num_processes = min(num_processes, batch_size)
        self._num_processes = num_processes
        self._batch_size = batch_size
        self._num_batches = num_batches
        self._restart_policy = (
            restart_policy if restart_policy is not None else RestartPolicy()
        )
        # Per-slot respawn timestamps for the quarantine window.
        self._restart_times: List[deque] = [deque() for _ in range(num_processes)]
        self._quarantined: set = set()  # slots past the policy: always raise
        # Issued batch-step counts; compared against the shm progress ledger.
        self._targets = [0] * num_batches
        # Set teardown state first: a ctor failure after shm allocation must
        # reach close() (named segments outlive the process if never
        # unlinked, unlike the anonymous mappings they replaced).
        self._closed = False
        self._segments = []
        self._doorbell_region = None
        self._task_queues: List = []
        self._procs: List = []
        self._worker_conns: List = []
        try:
            self._build(
                create_env, num_processes, batch_size, num_batches,
                action_dtype, action_shape,
            )
        except Exception:
            self.close()  # unlink any shm already allocated
            raise

    def _build(
        self, create_env, num_processes, batch_size, num_batches,
        action_dtype, action_shape,
    ):
        # Start-method contract (reference fork guard src/env.cc:149-169): a
        # plain fork() after the jax backend has started its threads is a
        # deadlock lottery, so fork is only chosen while jax is uninitialized.
        # Afterwards workers come from a forkserver — the server process is
        # launched via fork+exec (thread-safe) and its children are clean —
        # at the cost of create_env needing to be picklable.
        start = os.environ.get("MOOLIB_TPU_ENVPOOL_START")
        if start is None:
            start = "fork" if not _jax_backend_initialized() else "forkserver"
        if start == "forkserver":
            try:
                pickle.dumps(create_env)
            except Exception as e:
                raise RuntimeError(
                    "EnvPool after jax initialization uses the forkserver start "
                    f"method, which requires a picklable create_env ({e!r}). "
                    "Either construct the EnvPool before the first jax backend "
                    "use (preferred; the reference forks early for the same "
                    "reason), or pass a module-level function / functools."
                    "partial instead of a closure."
                ) from e
        ctx = mp.get_context(start)

        # The shm resource tracker must exist BEFORE the first worker forks,
        # so every worker inherits the parent's tracker.  A worker that has
        # to spawn its own (possible in the mp-doorbell fallback, where no
        # shm exists pre-fork) takes that private tracker down with it when
        # SIGKILLed — and the dying tracker unlinks every segment the worker
        # had attached, yanking live obs/act buffers out from under the
        # whole pool (observed as FileNotFoundError on respawn re-attach).
        try:
            from multiprocessing import resource_tracker

            resource_tracker.ensure_running()
        except Exception:  # noqa: BLE001 — platform without the tracker
            pass

        # 1. Spawn worker 0 first: it discovers the observation spec from its
        # own first env (which it keeps and steps) — the shm layout derives
        # from a real first observation, reference ``src/env.h:214-246``.
        self._task_queues, self._done_sems, self._doorbell_region, doorbell_desc = (
            _make_doorbells(ctx, num_processes, num_batches)
        )
        per = batch_size // num_processes
        extra = batch_size % num_processes
        bounds = []
        lo = 0
        for w in range(num_processes):
            hi = lo + per + (1 if w < extra else 0)
            bounds.append((lo, hi))
            lo = hi

        # Saved so a dead worker can be respawned later with identical
        # arguments and re-attached to the same shm/doorbell descriptors.
        self._ctx = ctx
        self._create_env = create_env
        self._bounds = bounds
        self._doorbell_desc = doorbell_desc
        self._layout = None

        p0, p0conn = self._spawn(0, discover=True)
        self._procs = [p0]
        self._worker_conns = [p0conn]
        if not p0conn.poll(60):
            raise RuntimeError("EnvPool: env spec discovery timed out")
        status, spec = p0conn.recv()
        if status != "ok":
            raise RuntimeError(f"EnvPool: create_env failed in worker 0: {spec}")
        for key in _FIELD_RESERVED:
            if key in spec:
                raise ValueError(f"observation key {key!r} is reserved")

        # 2. Allocate shared memory: per batch, [batch_size, *obs_shape] per
        # key + reward/done + actions.
        self._segments: List[shared_memory.SharedMemory] = []
        self._obs_views: List[Dict[str, np.ndarray]] = []
        self._act_views: List[np.ndarray] = []
        layout_obs, layout_act = [], []
        full_spec = dict(spec)
        full_spec["reward"] = ((), "<f4")
        full_spec["done"] = ((), "|b1")
        for b in range(num_batches):
            views, meta = {}, {}
            for key, (shape, dtype) in full_spec.items():
                arr_shape = (batch_size, *shape)
                nbytes = int(np.prod(arr_shape, dtype=np.int64)) * np.dtype(dtype).itemsize
                seg = shared_memory.SharedMemory(create=True, size=max(nbytes, 1))
                self._segments.append(seg)
                views[key] = np.ndarray(arr_shape, dtype=dtype, buffer=seg.buf)
                views[key].fill(0)
                meta[key] = (seg.name, arr_shape, dtype)
            self._obs_views.append(views)
            layout_obs.append(meta)
            act_shape = (batch_size, *action_shape)
            seg = shared_memory.SharedMemory(
                create=True, size=int(np.prod(act_shape, dtype=np.int64) or 1) * np.dtype(action_dtype).itemsize
            )
            self._segments.append(seg)
            av = np.ndarray(act_shape, dtype=action_dtype, buffer=seg.buf)
            av.fill(0)
            self._act_views.append(av)
            layout_act.append((seg.name, act_shape, np.dtype(action_dtype).str))

        # Completion ledger (see EnvRunner.run): one int64 cell per
        # (batch, worker), zero-initialized alongside the data segments.
        prog_shape = (num_batches, num_processes)
        seg = shared_memory.SharedMemory(
            create=True, size=int(np.prod(prog_shape, dtype=np.int64)) * 8
        )
        self._segments.append(seg)
        self._progress = np.ndarray(prog_shape, dtype=np.int64, buffer=seg.buf)
        self._progress.fill(0)
        layout_progress = (seg.name, prog_shape, "<i8")

        # 3. Ship the layout to worker 0 and spawn the rest with it.
        layout = {"obs": layout_obs, "act": layout_act, "progress": layout_progress}
        self._layout = layout
        p0conn.send(layout)
        for w in range(1, num_processes):
            p, pconn = self._spawn(w)
            pconn.send(layout)
            self._procs.append(p)
            self._worker_conns.append(pconn)
        self._stepper = EnvStepper(self)
        _M_WORKERS.inc(num_processes)

    def _spawn(self, w: int, discover: bool = False):
        """Start (or restart) worker ``w`` attached to the pool's doorbell
        descriptor; the caller sends ``self._layout`` over the returned pipe
        (except for the discovery worker, which gets it after spec probe)."""
        pconn, cconn = self._ctx.Pipe()
        p = self._ctx.Process(
            target=_worker_main,
            args=(
                self._create_env,
                w,
                self._bounds[w][0],
                self._bounds[w][1],
                self._num_batches,
                cconn,
                _worker_doorbell_desc(self._doorbell_desc, w),
                discover,
            ),
            daemon=True,
        )
        p.start()
        return p, pconn

    def _batch_complete(self, b: int) -> bool:
        """True once every worker's progress cell reached the issued count."""
        return bool((self._progress[b] >= self._targets[b]).all())

    def _abort_batch(self, b: int) -> None:
        """Failure-path cleanup: clear the in-flight future and drain the
        completion semaphore so a failed step can't wedge the next
        ``step()`` on this batch or teardown (stale permits / a stuck
        'already in flight' slot)."""
        st = getattr(self, "_stepper", None)
        if st is None:
            return
        st._inflight[b] = None
        try:
            while st._done_sems[b].acquire(timeout=0):
                pass
        except Exception:  # noqa: BLE001 — best-effort drain during teardown
            pass

    def _check_workers(self) -> None:
        """Service worker health: raise env exceptions with their real
        traceback, and supervise unexplained deaths — respawn + re-attach
        per ``RestartPolicy``, quarantining slots that keep dying."""
        for i in range(self._num_processes):
            p, conn = self._procs[i], self._worker_conns[i]
            try:
                while conn.poll():
                    msg = conn.recv()
                    if isinstance(msg, tuple) and msg and msg[0] == "step_error":
                        raise RuntimeError(
                            f"EnvPool worker {msg[1]} env exception:\n{msg[2]}"
                        )
            except (EOFError, OSError):
                pass
            if not p.is_alive():
                self._supervise_dead_worker(i)

    def _supervise_dead_worker(self, i: int) -> None:
        """Worker ``i`` died without an env traceback (SIGKILL, OOM, hard
        crash): respawn it onto the existing shm segments/doorbells and
        re-issue any in-flight batch steps it never completed, unless the
        restart policy says the slot is beyond saving.  The death-detected →
        respawned-and-reissued interval lands in the shared
        ``recovery_seconds{phase="worker_respawn"}`` histogram so worker and
        peer recovery read off one metric family (docs/RESILIENCE.md)."""
        t_detect = time.monotonic()
        p = self._procs[i]
        exitcode = p.exitcode
        policy = self._restart_policy
        if not policy.enabled or policy.max_restarts <= 0:
            raise RuntimeError(f"EnvPool worker {i} died (exit code {exitcode})")
        now = time.monotonic()
        window = self._restart_times[i]
        while window and now - window[0] > policy.window:
            window.popleft()
        if i in self._quarantined or len(window) >= policy.max_restarts:
            if i not in self._quarantined:
                self._quarantined.add(i)
                _M_QUARANTINED.inc()
            raise RuntimeError(
                f"EnvPool worker {i} quarantined: died {len(window) + 1} times "
                f"within {policy.window:.0f}s (last exit code {exitcode}); "
                "the env or host is unhealthy beyond respawn"
            )
        window.append(now)
        utils.log_error(
            "envpool: worker %d died (exit code %s); respawning (%d/%d in %.0fs window)",
            i, exitcode, len(window), policy.max_restarts, policy.window,
        )
        try:
            p.join(timeout=0)  # reap the zombie
        except Exception:  # noqa: BLE001
            pass
        try:
            self._worker_conns[i].close()
        except Exception:  # noqa: BLE001
            pass
        # Tasks the dead worker never popped are still queued; the respawn
        # below recomputes what to run from the progress ledger, so drain
        # them or re-issued batches would be stepped twice.
        try:
            self._task_queues[i].drain()
        except Exception:  # noqa: BLE001
            pass
        proc, conn = self._spawn(i)
        conn.send(self._layout)
        self._procs[i] = proc
        self._worker_conns[i] = conn
        _M_RESTARTS.inc()
        # Re-issue in-flight steps this worker hadn't finished: envs in its
        # slice are recreated lazily on the respawn's first step of each
        # batch, the slice is rewritten whole, and the pending
        # EnvStepperFuture completes through the progress ledger.
        st = getattr(self, "_stepper", None)
        if st is not None:
            for b in range(self._num_batches):
                if st._inflight[b] is not None and self._progress[b, i] < self._targets[b]:
                    self._task_queues[i].put(b)
        telemetry.observe_phase("worker_respawn", time.monotonic() - t_detect)

    def step(self, batch_index: int, action) -> EnvStepperFuture:
        if not 0 <= batch_index < self._num_batches:
            raise ValueError(f"batch_index {batch_index} out of range")
        return self._stepper.step(batch_index, action)

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def obs_spec(self) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
        """Per-env observation spec ``{key: (shape, dtype)}`` discovered from
        worker 0's first reset (reward/done included).  Callers sizing
        device-side rollout buffers read the env's native dtype here —
        uint8 frames must cross the host boundary as uint8."""
        return {
            k: (v.shape[1:], v.dtype) for k, v in self._obs_views[0].items()
        }

    @property
    def num_batches(self) -> int:
        return self._num_batches

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if getattr(self, "_stepper", None) is not None:
            # The gauge only counted fully-built pools (_build's last line).
            _M_WORKERS.dec(self._num_processes)
        for q in self._task_queues:
            try:
                q.put(_SHUTDOWN)
            except Exception:
                pass
        # Close the pipes first: a worker still blocked in its layout recv
        # (ctor failed between spec discovery and layout send) wakes with
        # EOFError and exits instead of eating the 5 s join timeout.
        for conn in self._worker_conns:
            try:
                conn.close()
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for seg in self._segments:
            try:
                seg.close()
                seg.unlink()
            except Exception:
                pass
        if self._doorbell_region is not None:
            try:
                self._doorbell_region.unlink()
            except Exception:
                pass

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
