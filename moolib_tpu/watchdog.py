"""Run-loop deadman timer (docs/RESILIENCE.md).

A *wedged* distributed run is worse than a dead one: it burns accelerator
reservations while reporting nothing, and the scheduler has no reason to
restart it.  The reference moolib has no answer beyond operator attention;
here every training loop can arm a :class:`Watchdog` around each section it
executes (env step, reduce, train step)::

    wd = Watchdog(timeout=120.0)
    ...
    with wd.section("env_step"):
        obs = fut.result()

If a section overruns its deadline, a monitor thread

1. dumps the telemetry registry and the python stack of every live thread
   through the same path the SIGUSR1 handler uses
   (:func:`moolib_tpu.telemetry.exporters.dump_diagnostics`) — the triage
   artifact for "where was it stuck";
2. either invokes the ``on_expire`` hook (e.g. "save a checkpoint, then
   exit") or raises :class:`WatchdogTimeout` *inside the armed thread* so
   the loop's ``finally`` blocks run and the run ends with a resumable
   checkpoint instead of hanging silently.

The in-thread raise uses CPython's async-exception channel
(``PyThreadState_SetAsyncExc``), which delivers when the target thread next
executes bytecode.  The framework's wait loops all poll with sub-second
timeouts, so delivery is prompt; a thread blocked indefinitely inside a C
call would only see the exception on return (the diagnostics dump has
already fired by then).  A ``timeout`` of ``None``/``0`` disables the
watchdog entirely — ``section()`` becomes a no-op — so loops can wire it
unconditionally and let a flag decide.
"""

from __future__ import annotations

import contextlib
import ctypes
import threading
import time
from typing import Callable, Iterator, List, Optional, Tuple

from . import telemetry, utils
from .telemetry.exporters import dump_diagnostics

__all__ = ["Watchdog", "WatchdogTimeout"]

_REG = telemetry.get_registry()
_M_EXPIRED = _REG.counter(
    "watchdog_expirations_total", "armed sections that exceeded their deadline"
)


class WatchdogTimeout(RuntimeError):
    """An armed watchdog section exceeded its deadline."""


def _raise_in_thread(tid: int) -> None:
    """Deliver WatchdogTimeout to ``tid`` via the async-exception channel.
    A target that no longer exists (res == 0) is just logged — the wedge
    resolved itself by dying, and interrupting some *other* healthy thread
    would turn a recovered run into a dead one.  The diagnostics dump has
    already happened by this point either way."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(WatchdogTimeout)
    )
    if res == 1:
        return
    if res > 1:  # hit more than one thread state: undo (should not happen)
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)
    utils.log_error(
        "watchdog: could not deliver WatchdogTimeout to thread %d (res=%d)",
        tid, res,
    )


class Watchdog:
    """Deadman timer for training-loop sections.

    One watchdog instance serves a whole loop: ``section(name)`` arms a
    deadline for its body and disarms on exit; overlapping/nested sections
    are independent arms.  ``arm()``/``feed()``/``disarm()`` expose the same
    machinery for non-``with`` shapes (e.g. "whole iteration" deadlines fed
    once per pass).  The monitor thread starts lazily on the first arm and
    is a daemon — an idle watchdog costs nothing and never blocks exit.
    """

    def __init__(
        self,
        timeout: Optional[float] = None,
        on_expire: Optional[Callable[[str, float], None]] = None,
        name: str = "",
        run_dir: Optional[str] = None,
        dump: bool = True,
        poll_interval: Optional[float] = None,
    ):
        self._timeout = float(timeout) if timeout and timeout > 0 else None
        self._on_expire = on_expire
        self._name = name
        self._run_dir = run_dir
        self._dump = dump
        self._poll = poll_interval
        self._lock = threading.Lock()
        # token -> (section, deadline, thread_ident, timeout)
        self._arms: dict = {}
        # Tokens currently being fired (dump in progress): kept in _arms so
        # disarm() can still cancel the pending raise, but not re-collected.
        self._firing: set = set()
        self._next_token = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: (section, timeout) records of every expiry, oldest first.
        self.expired: List[Tuple[str, float]] = []

    @property
    def enabled(self) -> bool:
        return self._timeout is not None

    # ------------------------------------------------------------------ arms
    def arm(
        self,
        section: str = "",
        timeout: Optional[float] = None,
        thread_id: Optional[int] = None,
    ) -> Optional[int]:
        """Start a deadline; returns a token for feed()/disarm(), or None
        when the effective timeout is disabled."""
        t = self._timeout if timeout is None else (
            float(timeout) if timeout and timeout > 0 else None
        )
        if t is None:
            return None
        tid = thread_id if thread_id is not None else threading.get_ident()
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._arms[token] = (section, time.monotonic() + t, tid, t)
            self._ensure_thread()
        return token

    def feed(self, token: Optional[int]) -> None:
        """Push the deadline of an armed token back by its full timeout
        (per-iteration heartbeat for long-lived arms)."""
        if token is None:
            return
        with self._lock:
            a = self._arms.get(token)
            if a is not None:
                self._arms[token] = (a[0], time.monotonic() + a[3], a[2], a[3])

    def disarm(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._arms.pop(token, None)

    @contextlib.contextmanager
    def section(self, name: str, timeout: Optional[float] = None) -> Iterator[None]:
        """Arm around a loop section; a no-op when the watchdog is disabled."""
        token = self.arm(name, timeout)
        try:
            yield
        finally:
            self.disarm(token)

    # --------------------------------------------------------------- monitor
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run,
                name=f"watchdog-{self._name or 'loop'}",
                daemon=True,
            )
            self._thread.start()

    def _interval(self) -> float:
        if self._poll:
            return self._poll
        base = self._timeout if self._timeout is not None else 1.0
        return max(0.05, min(0.25, base / 4))

    def _run(self) -> None:
        while not self._stop.wait(self._interval()):
            now = time.monotonic()
            fired = []
            with self._lock:
                for token, (sec, deadline, tid, t) in list(self._arms.items()):
                    if now > deadline and token not in self._firing:
                        self._firing.add(token)  # fire once per arm
                        fired.append((token, sec, tid, t))
            for token, sec, tid, t in fired:
                self._fire(token, sec, tid, t)

    def _fire(self, token: int, section: str, tid: int, timeout: float) -> None:
        _M_EXPIRED.inc()
        self.expired.append((section, timeout))
        label = f"watchdog {self._name!r}" if self._name else "watchdog"
        reason = f"{label}: section {section!r} exceeded its {timeout:.1f}s deadline"
        utils.log_error("%s", reason)
        # Before the dump: the expiry itself must appear in the flight
        # recorder tail the dump prints.
        telemetry.flight_event("watchdog.expired", watchdog=self._name,
                               section=section, timeout_s=timeout)
        if self._dump:
            try:
                dump_diagnostics(reason=reason, run_dir=self._run_dir)
            except Exception:  # noqa: BLE001 — diagnostics must not mask the expiry
                pass
        # The dump above is slow (thread stacks, maybe a trace write): the
        # section may have legitimately finished in the meantime.  disarm()
        # wins that race — a raise delivered AFTER the section completed
        # would kill an arbitrary later bytecode (e.g. mid-teardown) of a
        # run that in fact recovered.
        with self._lock:
            still_armed = self._arms.pop(token, None) is not None
            self._firing.discard(token)
        if not still_armed:
            utils.log_error(
                "%s — but the section completed during diagnostics; not raising",
                reason,
            )
            return
        if self._on_expire is not None:
            try:
                self._on_expire(section, timeout)
            except Exception as e:  # noqa: BLE001
                utils.log_error("watchdog on_expire hook failed: %r", e)
            return
        _raise_in_thread(tid)

    def close(self) -> None:
        """Disarm everything and stop the monitor thread."""
        self._stop.set()
        with self._lock:
            self._arms.clear()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=1.0)
        self._thread = None

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
