"""Batcher: assemble pytrees into device-resident batches.

Counterpart of the reference's C++ ``Batcher`` (``src/moolib.cc:595-889,
1411-1488``; ctor args size/device/dim at ``:1888``): accumulate pytree items
by ``stack`` (one slot per call along a new axis ``dim``) or ``cat``
(concatenate along existing axis ``dim``, with arbitrary-length items split
across batch boundaries — the carry-over path, reference ``:767-811``).  When
a batch fills, ``get()`` returns it; ``empty()``/``size()`` poll; awaiting
the batcher yields filled batches in asyncio code.

TPU-first: instead of preallocating torch storage on a CUDA device and
copying slot-by-slot, items are accumulated as host numpy and the completed
batch goes to the accelerator in one ``jax.device_put`` of the whole stacked
pytree (one contiguous host→HBM DMA per leaf; a ``jax.sharding.Sharding``
may be passed as ``device`` to land the batch pre-sharded across a mesh).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional

import jax
import numpy as np

from . import telemetry
from .utils import nest

# Batch-assembly metrics (docs/TELEMETRY.md): how full batches run and how
# long completed batches sit ready before the consumer drains them (a
# persistent ready-wait means the learner, not assembly, is the bottleneck).
_REG = telemetry.get_registry()
_M_BATCHES = _REG.counter("batcher_batches_total", "completed batches")
_M_ITEMS = _REG.counter("batcher_items_total", "rows batched (batch-axis length)")
_M_READY_DEPTH = _REG.gauge("batcher_ready_depth", "completed batches awaiting get()")
_M_READY_WAIT = _REG.histogram(
    "batcher_ready_wait_seconds", "batch completion to get()/await"
)


def _resolve_device(device):
    if device is None or isinstance(device, str) and device in ("cpu", ""):
        return None
    if isinstance(device, str):
        # "tpu", "tpu:0", "cuda:0"-style strings map to jax devices.
        kind, _, idx = device.partition(":")
        if kind == "cuda":  # reference configs say cuda; we run on TPU
            kind = "tpu"
        devs = [d for d in jax.devices() if d.platform.startswith(kind)]
        if not devs:
            devs = jax.devices()
        return devs[int(idx) if idx else 0]
    return device  # jax.Device or Sharding


class Batcher:
    """See module docstring. API: stack(item), cat(item), empty(), size(),
    get(), plus awaitable batches."""

    def __init__(self, size: int, device: Optional[str] = None, dim: int = 0):
        if size < 1:
            raise ValueError("batch size must be >= 1")
        self._size = size
        self._dim = dim
        self._device = _resolve_device(device)
        self._lock = threading.Lock()
        self._slots: List[Any] = []
        self._cat_count = 0
        self._ready: collections.deque = collections.deque()
        self._waiters: collections.deque = collections.deque()

    # ---------------------------------------------------------------- fill
    def stack(self, item) -> None:
        """Add one item; a batch completes after ``size`` calls (new axis)."""
        with self._lock:
            self._slots.append(item)
            if len(self._slots) >= self._size:
                items, self._slots = self._slots[: self._size], self._slots[self._size :]
                self._finish(nest.stack(items, dim=self._dim))

    def cat(self, item) -> None:
        """Add an item whose leaves already have the batch axis; completes
        when ``size`` rows accumulate, splitting oversized items (carry-over)."""
        with self._lock:
            length = self._item_length(item)
            offset = 0
            while offset < length:
                room = self._size - self._cat_count
                take = min(room, length - offset)
                part = (
                    item
                    if take == length and offset == 0
                    else nest.map(lambda x: self._slice(x, offset, take), item)
                )
                self._slots.append(part)
                self._cat_count += take
                offset += take
                if self._cat_count >= self._size:
                    items, self._slots = self._slots, []
                    self._cat_count = 0
                    self._finish(
                        items[0] if len(items) == 1 else nest.cat(items, dim=self._dim)
                    )

    def _item_length(self, item) -> int:
        leaves = list(nest.flatten(item))
        if not leaves:
            raise ValueError("empty item")
        return int(np.shape(leaves[0])[self._dim])

    def _slice(self, x, offset: int, take: int):
        idx = [slice(None)] * np.ndim(x)
        idx[self._dim] = slice(offset, offset + take)
        return x[tuple(idx)]

    def _finish(self, batch) -> None:
        # One device_put of the whole pytree: a single host->HBM hop per leaf.
        if self._device is not None:
            batch = jax.device_put(batch, self._device)
        _M_BATCHES.inc()
        _M_ITEMS.inc(self._size)
        if self._waiters:
            loop, af = self._waiters.popleft()
            _M_READY_WAIT.observe(0.0)  # a consumer was already waiting
            loop.call_soon_threadsafe(_set_result, af, batch)
        else:
            self._ready.append((batch, time.monotonic()))
            _M_READY_DEPTH.inc()

    # --------------------------------------------------------------- drain
    def empty(self) -> bool:
        with self._lock:
            return not self._ready

    def size(self) -> int:
        """Items currently buffered toward the next batch (reference ``size``)."""
        with self._lock:
            return self._cat_count if self._cat_count else len(self._slots)

    def get(self):
        with self._lock:
            if not self._ready:
                raise RuntimeError("Batcher.get() called with no complete batch")
            return self._pop_ready_locked()

    def _pop_ready_locked(self):
        batch, done_at = self._ready.popleft()
        _M_READY_DEPTH.dec()
        _M_READY_WAIT.observe(time.monotonic() - done_at)
        return batch

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        af = loop.create_future()
        with self._lock:
            if self._ready:
                af.set_result(self._pop_ready_locked())
            else:
                self._waiters.append((loop, af))
        return af.__await__()

    __iter__ = __await__


def _set_result(af, value):
    if not af.cancelled():
        af.set_result(value)
