"""Batcher: assemble pytrees into device-resident batches.

Counterpart of the reference's C++ ``Batcher`` (``src/moolib.cc:595-889,
1411-1488``; ctor args size/device/dim at ``:1888``): accumulate pytree items
by ``stack`` (one slot per call along a new axis ``dim``) or ``cat``
(concatenate along existing axis ``dim``, with arbitrary-length items split
across batch boundaries — the carry-over path, reference ``:767-811``).  When
a batch fills, ``get()`` returns it; ``empty()``/``size()`` poll; awaiting
the batcher yields filled batches in asyncio code.

TPU-first: instead of preallocating torch storage on a CUDA device and
copying slot-by-slot, items are accumulated as host numpy and the completed
batch goes to the accelerator in one ``jax.device_put`` of the whole stacked
pytree (one contiguous host→HBM DMA per leaf; a ``jax.sharding.Sharding``
may be passed as ``device`` to land the batch pre-sharded across a mesh).

Two assembly paths (docs/DESIGN.md "Actor data plane"):

- **host** (numpy items): leaves accumulate as host numpy — device-array
  leaves are coerced down (a D2H crossing, counted in
  ``batcher_d2h_bytes_total``) — and the completed batch crosses up in one
  ``device_put`` when a device is set (``batcher_h2d_bytes_total``).  This
  is the legacy rollout data plane: every batch pays a down-and-up round
  trip.
- **device** (jax.Array items, e.g. the unrolls a
  :class:`~moolib_tpu.rollout.DeviceRollout` hands over): leaves stay on
  the device; stack/cat/split run as XLA ops and the "completed batch" is
  device-resident already — zero host-boundary bytes.  ``device_put`` still
  applies a sharding when one was requested (mesh learners).

The path is latched from the first item's leaf type unless forced with the
``host=`` constructor argument.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional

import jax
import numpy as np

from . import telemetry
from .utils import nest

# Batch-assembly metrics (docs/TELEMETRY.md): how full batches run and how
# long completed batches sit ready before the consumer drains them (a
# persistent ready-wait means the learner, not assembly, is the bottleneck).
_REG = telemetry.get_registry()
_M_BATCHES = _REG.counter("batcher_batches_total", "completed batches")
_M_ITEMS = _REG.counter("batcher_items_total", "rows batched (batch-axis length)")
_M_READY_DEPTH = _REG.gauge("batcher_ready_depth", "completed batches awaiting get()")
_M_READY_WAIT = _REG.histogram(
    "batcher_ready_wait_seconds", "batch completion to get()/await"
)
# Host-boundary traffic of batch assembly (docs/TELEMETRY.md): the host path
# pays D2H per coerced device leaf and H2D per completed-batch device_put;
# the device path pays neither.
_M_D2H_BYTES = _REG.counter(
    "batcher_d2h_bytes_total", "device leaves coerced to host during assembly"
)
_M_H2D_BYTES = _REG.counter(
    "batcher_h2d_bytes_total", "completed host batches uploaded by device_put"
)
# Sebulba (arXiv:2104.06272): when the device path's target sharding lives on
# a DIFFERENT device set than the incoming leaves (actor submesh -> learner
# submesh), the batcher IS the inter-mesh queue and its device_put is the
# trajectory handoff — counted here, never in the host-boundary counters
# (the bytes ride ICI, not PCIe).
_M_D2D_BYTES = _REG.counter(
    "batcher_d2d_bytes_total",
    "device batches re-placed across device sets (inter-mesh handoff)",
)
# Flow control at the Sebulba seam (ROADMAP item 2): with ``max_outstanding``
# set, producers block once this many completed batches sit unconsumed —
# actor lead over the learner is bounded instead of growing without limit.
# Per-instance label so the autoscaler can tell the learn queue from others.
_M_QUEUE_DEPTH = _REG.gauge(
    "batcher_queue_depth",
    "completed batches held in the (optionally bounded) ready queue",
    ("batcher",),
)
_M_PUT_BLOCKED = _REG.histogram(
    "batcher_put_blocked_seconds",
    "producer time spent blocked on a full bounded ready queue",
    ("batcher",),
)


def _host_stack_leaves(xs, dim):
    """numpy counterpart of ``nest._stack_leaves`` (same object-leaf
    fallback) — the host path must never bounce through jnp."""
    try:
        return np.stack(xs, axis=dim)
    except (TypeError, ValueError):
        out = np.empty(len(xs), dtype=object)
        for i, x in enumerate(xs):
            out[i] = x
        return out


def _resolve_device(device):
    if device is None or isinstance(device, str) and device in ("cpu", ""):
        return None
    if isinstance(device, str):
        # "tpu", "tpu:0", "cuda:0"-style strings map to jax devices.
        kind, _, idx = device.partition(":")
        if kind == "cuda":  # reference configs say cuda; we run on TPU
            kind = "tpu"
        devs = [d for d in jax.devices() if d.platform.startswith(kind)]
        if not devs:
            devs = jax.devices()
        return devs[int(idx) if idx else 0]
    return device  # jax.Device or Sharding


class Batcher:
    """See module docstring. API: stack(item), cat(item), empty(), size(),
    get(), plus awaitable batches."""

    def __init__(self, size: int, device: Optional[str] = None, dim: int = 0,
                 host: Optional[bool] = None,
                 max_outstanding: Optional[int] = None, name: str = "batcher"):
        if size < 1:
            raise ValueError("batch size must be >= 1")
        if max_outstanding is not None and max_outstanding < 1:
            raise ValueError("max_outstanding must be >= 1 (or None = unbounded)")
        self._size = size
        self._dim = dim
        self._device = _resolve_device(device)
        # None = latch from the first item: jax.Array leaves keep the
        # device-side path (XLA stack/cat, no crossings), anything else
        # accumulates as host numpy.  True/False forces a path.
        self._host = host
        # Bounded ready queue: with max_outstanding set, the producer's
        # stack()/cat() BLOCKS once this many completed batches await get()
        # — backpressure instead of unbounded actor lead.  None keeps the
        # legacy unbounded behavior (and can never deadlock single-threaded
        # fill-then-drain code).
        self._max_outstanding = max_outstanding
        self._name = name
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._slots: List[Any] = []
        self._cat_count = 0
        self._ready: collections.deque = collections.deque()
        self._waiters: collections.deque = collections.deque()

    def _latch_path(self, item) -> None:
        if self._host is None:
            leaf = next(nest.flatten(item), None)
            self._host = not isinstance(leaf, jax.Array)

    def _to_host(self, item):
        """Host-path coercion: device leaves come down (counted D2H)."""

        def _coerce(x):
            if isinstance(x, jax.Array):
                out = np.asarray(x)
                _M_D2H_BYTES.inc(out.nbytes)
                return out
            return x

        return nest.map(_coerce, item)

    def _assemble(self, items):
        """Stack slot items into a batch on the latched path."""
        if self._host:
            return nest.map_many(
                lambda *xs: _host_stack_leaves(xs, self._dim), *items
            )
        return nest.stack(items, dim=self._dim)

    def _assemble_cat(self, items):
        if self._host:
            return nest.map_many(
                lambda *xs: np.concatenate(xs, axis=self._dim), *items
            )
        return nest.cat(items, dim=self._dim)

    # ---------------------------------------------------------------- fill
    def stack(self, item) -> None:
        """Add one item; a batch completes after ``size`` calls (new axis)."""
        with self._lock:
            self._latch_path(item)
            if self._host:
                item = self._to_host(item)
            self._slots.append(item)
            if len(self._slots) >= self._size:
                items, self._slots = self._slots[: self._size], self._slots[self._size :]
                self._finish(self._assemble(items))

    def cat(self, item) -> None:
        """Add an item whose leaves already have the batch axis; completes
        when ``size`` rows accumulate, splitting oversized items (carry-over)."""
        with self._lock:
            self._latch_path(item)
            if self._host:
                item = self._to_host(item)
            length = self._item_length(item)
            offset = 0
            while offset < length:
                room = self._size - self._cat_count
                take = min(room, length - offset)
                part = (
                    item
                    if take == length and offset == 0
                    else nest.map(lambda x: self._slice(x, offset, take), item)
                )
                self._slots.append(part)
                self._cat_count += take
                offset += take
                if self._cat_count >= self._size:
                    items, self._slots = self._slots, []
                    self._cat_count = 0
                    self._finish(
                        items[0] if len(items) == 1 else self._assemble_cat(items)
                    )

    def _item_length(self, item) -> int:
        leaves = list(nest.flatten(item))
        if not leaves:
            raise ValueError("empty item")
        return int(np.shape(leaves[0])[self._dim])

    def _slice(self, x, offset: int, take: int):
        idx = [slice(None)] * np.ndim(x)
        idx[self._dim] = slice(offset, offset + take)
        return x[tuple(idx)]

    def _target_devices(self):
        d = self._device
        if hasattr(d, "device_set"):  # jax.sharding.Sharding
            return frozenset(d.device_set)
        return frozenset((d,))

    def _finish(self, batch) -> None:
        # Backpressure BEFORE the device_put: a blocked producer must not keep
        # uploading batches to device memory.  wait() releases the lock, so
        # consumers drain (get()/await notify via _pop_ready_locked).  A
        # waiter present means immediate handoff — no queue growth, no block.
        if self._max_outstanding is not None:
            t0 = None
            while len(self._ready) >= self._max_outstanding and not self._waiters:
                if t0 is None:
                    t0 = time.monotonic()
                self._not_full.wait()
            if t0 is not None:
                _M_PUT_BLOCKED.observe(time.monotonic() - t0, batcher=self._name)
        # One device_put of the whole pytree: a single host->HBM hop per leaf.
        if self._device is not None:
            if self._host:
                _M_H2D_BYTES.inc(
                    sum(getattr(x, "nbytes", 0) for x in nest.flatten(batch))
                )
            else:
                # Device path: a same-device-set put is a no-op/reshard; a
                # cross-set put is the Sebulba actor->learner handoff.
                tgt = self._target_devices()
                moved = sum(
                    x.nbytes
                    for x in nest.flatten(batch)
                    if isinstance(x, jax.Array)
                    and frozenset(x.sharding.device_set) != tgt
                )
                if moved:
                    _M_D2D_BYTES.inc(moved)
            batch = jax.device_put(batch, self._device)
        _M_BATCHES.inc()
        _M_ITEMS.inc(self._size)
        if self._waiters:
            loop, af = self._waiters.popleft()
            _M_READY_WAIT.observe(0.0)  # a consumer was already waiting
            loop.call_soon_threadsafe(_set_result, af, batch)
        else:
            self._ready.append((batch, time.monotonic()))
            _M_READY_DEPTH.inc()
            _M_QUEUE_DEPTH.set(len(self._ready), batcher=self._name)

    # --------------------------------------------------------------- drain
    def empty(self) -> bool:
        with self._lock:
            return not self._ready

    def size(self) -> int:
        """Items currently buffered toward the next batch (reference ``size``)."""
        with self._lock:
            return self._cat_count if self._cat_count else len(self._slots)

    def get(self):
        with self._lock:
            if not self._ready:
                raise RuntimeError("Batcher.get() called with no complete batch")
            return self._pop_ready_locked()

    def _pop_ready_locked(self):
        batch, done_at = self._ready.popleft()
        _M_READY_DEPTH.dec()
        _M_QUEUE_DEPTH.set(len(self._ready), batcher=self._name)
        _M_READY_WAIT.observe(time.monotonic() - done_at)
        self._not_full.notify()
        return batch

    def __await__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        af = loop.create_future()
        with self._lock:
            if self._ready:
                af.set_result(self._pop_ready_locked())
            else:
                self._waiters.append((loop, af))
        return af.__await__()

    __iter__ = __await__


def _set_result(af, value):
    if not af.cancelled():
        af.set_result(value)
