"""IMPALA deep ResNet policy network in flax.

Architecture parity with the reference ``examples/atari/models.py:9-153``:
3 sections (16/32/32 channels by default), each = conv3x3 → maxpool3x3/2 →
two 2-conv residual blocks; flatten → FC-256 → concat(one-hot prev action,
clipped reward) → optional LSTM with done-masked state resets → policy +
baseline heads.  Differences are TPU-idiomatic, not cosmetic:

- NHWC layout (XLA's native conv layout on TPU) instead of NCHW;
- configurable compute dtype (bfloat16 by default keeps the convs on the
  MXU at full rate; params stay float32, heads computed in float32);
- the LSTM unroll is ``nn.scan`` (one fused XLA while-loop, no python loop);
- action sampling is an explicit jax PRNG argument, not hidden global state.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class ResidualBlock(nn.Module):
    channels: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        y = nn.relu(x)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.channels, (3, 3), padding="SAME", dtype=self.dtype)(y)
        return x + y


class ImpalaEncoder(nn.Module):
    channels: Sequence[int] = (16, 32, 32)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            x = ResidualBlock(ch, self.dtype)(x)
            x = ResidualBlock(ch, self.dtype)(x)
        return nn.relu(x)


class ImpalaNet(nn.Module):
    """Full IMPALA agent network. Call with time-major inputs:

    inputs = {"state": [T,B,H,W,C] uint8, "reward": [T,B] f32,
              "done": [T,B] bool, "prev_action": [T,B] i32}
    outputs: ({"policy_logits": [T,B,A] f32, "baseline": [T,B] f32,
               "action": [T,B] i32 (only when sample_rng given)}, core_state)
    """

    num_actions: int
    channels: Sequence[int] = (16, 32, 32)
    use_lstm: bool = False
    hidden_size: int = 256
    dtype: Any = jnp.bfloat16

    def initial_state(self, batch_size: int) -> Tuple:
        if not self.use_lstm:
            return ()
        return (
            jnp.zeros((batch_size, self.hidden_size), jnp.float32),
            jnp.zeros((batch_size, self.hidden_size), jnp.float32),
        )

    @nn.compact
    def __call__(self, inputs, core_state=(), sample_rng: Optional[jax.Array] = None):
        x = inputs["state"]
        T, B = x.shape[0], x.shape[1]
        x = x.reshape(T * B, *x.shape[2:])
        x = x.astype(self.dtype) / 255.0
        x = ImpalaEncoder(self.channels, self.dtype)(x)
        x = x.reshape(T * B, -1)
        x = nn.relu(nn.Dense(self.hidden_size, dtype=self.dtype)(x))

        one_hot_prev = jax.nn.one_hot(
            inputs["prev_action"].reshape(T * B), self.num_actions, dtype=self.dtype
        )
        clipped_reward = jnp.clip(inputs["reward"], -1, 1).reshape(T * B, 1).astype(self.dtype)
        core_input = jnp.concatenate([x, clipped_reward, one_hot_prev], axis=-1)

        if self.use_lstm:
            core_input = core_input.reshape(T, B, -1)
            notdone = (~inputs["done"]).astype(jnp.float32)

            class _Core(nn.Module):
                hidden: int

                @nn.compact
                def __call__(self, carry, xs):
                    inp, nd = xs
                    # Reset the state to zeros where an episode ended.
                    carry = jax.tree_util.tree_map(lambda s: s * nd[:, None], carry)
                    carry, out = nn.OptimizedLSTMCell(self.hidden)(carry, inp)
                    return carry, out

            scan_core = nn.scan(
                _Core,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(self.hidden_size)
            core_state, core_output = scan_core(
                tuple(core_state), (core_input.astype(jnp.float32), notdone)
            )
            core_output = core_output.reshape(T * B, -1)
        else:
            core_output = core_input

        # Heads in float32 for stable logits/values.
        policy_logits = nn.Dense(self.num_actions, dtype=jnp.float32)(
            core_output.astype(jnp.float32)
        )
        baseline = nn.Dense(1, dtype=jnp.float32)(core_output.astype(jnp.float32))

        out = {
            "policy_logits": policy_logits.reshape(T, B, self.num_actions),
            "baseline": baseline.reshape(T, B),
        }
        if sample_rng is not None:
            out["action"] = jax.random.categorical(sample_rng, out["policy_logits"])
        return out, core_state
