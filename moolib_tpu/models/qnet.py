"""Recurrent Q-network for the R2D2-family example (FC → LSTM → Q-values).

Same call contract as the other models: time-major input dict →
({"q": [T,B,A]}, core_state).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class RecurrentQNet(nn.Module):
    num_actions: int
    hidden_size: int = 128
    core_size: int = 64
    use_lstm: bool = True
    dtype: Any = jnp.float32

    def initial_state(self, batch_size: int) -> Tuple:
        if not self.use_lstm:
            return ()
        return (
            jnp.zeros((batch_size, self.core_size), jnp.float32),
            jnp.zeros((batch_size, self.core_size), jnp.float32),
        )

    @nn.compact
    def __call__(self, inputs, core_state=()):
        x = inputs["state"]
        T, B = x.shape[0], x.shape[1]
        x = x.reshape(T * B, -1).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden_size, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.core_size, dtype=self.dtype)(x))

        if self.use_lstm:
            x = x.reshape(T, B, -1)
            notdone = (~inputs["done"]).astype(jnp.float32)

            class _Core(nn.Module):
                hidden: int

                @nn.compact
                def __call__(self, carry, xs):
                    inp, nd = xs
                    carry = jax.tree_util.tree_map(lambda s: s * nd[:, None], carry)
                    carry, out = nn.OptimizedLSTMCell(self.hidden)(carry, inp)
                    return carry, out

            scan_core = nn.scan(
                _Core,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(self.core_size)
            core_state, x = scan_core(tuple(core_state), (x.astype(jnp.float32), notdone))
            x = x.reshape(T * B, -1)

        # Dueling heads: V + (A - mean A).
        value = nn.Dense(1, dtype=jnp.float32)(x.astype(jnp.float32))
        adv = nn.Dense(self.num_actions, dtype=jnp.float32)(x.astype(jnp.float32))
        q = value + adv - adv.mean(axis=-1, keepdims=True)
        return {"q": q.reshape(T, B, self.num_actions)}, core_state
