"""Recurrent Q-network for the R2D2-family example (encoder → LSTM → Q).

Same call contract as the other models: time-major input dict →
({"q": [T,B,A]}, core_state).  ``encoder="mlp"`` (default) consumes flat
vector states; ``encoder="impala"`` consumes [T,B,H,W,C] uint8 frames
through the shared IMPALA ResNet — the classic R2D2-on-Atari shape
(B=64 sequences of T=80 at 84×84×4), which is what
``benchmarks/r2d2_bench.py`` times on chip.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .impala import ImpalaEncoder


class RecurrentQNet(nn.Module):
    num_actions: int
    hidden_size: int = 128
    core_size: int = 64
    use_lstm: bool = True
    dtype: Any = jnp.float32
    encoder: str = "mlp"  # mlp (vector states) | impala (pixel frames)
    channels: Sequence[int] = (16, 32, 32)  # impala encoder widths

    def initial_state(self, batch_size: int) -> Tuple:
        if not self.use_lstm:
            return ()
        return (
            jnp.zeros((batch_size, self.core_size), jnp.float32),
            jnp.zeros((batch_size, self.core_size), jnp.float32),
        )

    @nn.compact
    def __call__(self, inputs, core_state=()):
        x = inputs["state"]
        T, B = x.shape[0], x.shape[1]
        if self.encoder == "impala":
            x = x.reshape(T * B, *x.shape[2:])
            x = x.astype(self.dtype) / 255.0
            x = ImpalaEncoder(self.channels, self.dtype)(x)
            x = x.reshape(T * B, -1)
        elif self.encoder == "mlp":
            x = x.reshape(T * B, -1).astype(self.dtype)
        else:
            raise ValueError(f"unknown encoder {self.encoder!r}")
        x = nn.relu(nn.Dense(self.hidden_size, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.core_size, dtype=self.dtype)(x))

        if self.use_lstm:
            x = x.reshape(T, B, -1)
            notdone = (~inputs["done"]).astype(jnp.float32)

            class _Core(nn.Module):
                hidden: int

                @nn.compact
                def __call__(self, carry, xs):
                    inp, nd = xs
                    carry = jax.tree_util.tree_map(lambda s: s * nd[:, None], carry)
                    carry, out = nn.OptimizedLSTMCell(self.hidden)(carry, inp)
                    return carry, out

            scan_core = nn.scan(
                _Core,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(self.core_size)
            core_state, x = scan_core(tuple(core_state), (x.astype(jnp.float32), notdone))
            x = x.reshape(T * B, -1)

        # Dueling heads: V + (A - mean A).
        value = nn.Dense(1, dtype=jnp.float32)(x.astype(jnp.float32))
        adv = nn.Dense(self.num_actions, dtype=jnp.float32)(x.astype(jnp.float32))
        q = value + adv - adv.mean(axis=-1, keepdims=True)
        return {"q": q.reshape(T, B, self.num_actions)}, core_state
