"""Model zoo (flax.linen): IMPALA ResNet, recurrent actor-critic cores.

TPU-native re-design of the reference's model layer
(``examples/atari/models.py:9-153``, ``examples/a2c.py:52-114``): same
architectures and input/output contract — time-major input dict
``{"state", "reward", "done", "prev_action"}`` → ``({"policy_logits",
"baseline"[, "action"]}, core_state)`` — built in flax with bfloat16 compute
support so the convs/matmuls land on the MXU.
"""

from .impala import ImpalaNet  # noqa: F401
from .actor_critic import ActorCriticNet  # noqa: F401
from .qnet import RecurrentQNet  # noqa: F401
from .transformer import TransformerLM  # noqa: F401
