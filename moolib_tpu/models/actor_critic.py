"""Small recurrent actor-critic for vector observations (CartPole-class).

Counterpart of the reference A2C example model (``examples/a2c.py:52-114``:
FC → LSTM → policy + baseline heads) with the same call contract as
:class:`moolib_tpu.models.ImpalaNet`.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class ActorCriticNet(nn.Module):
    """Reference shape: FC-128 → FC-32 → (LSTM-32) → heads
    (``examples/a2c.py:55-66``)."""

    num_actions: int
    hidden_size: int = 128
    core_size: int = 32
    use_lstm: bool = True
    dtype: Any = jnp.float32

    def initial_state(self, batch_size: int) -> Tuple:
        if not self.use_lstm:
            return ()
        return (
            jnp.zeros((batch_size, self.core_size), jnp.float32),
            jnp.zeros((batch_size, self.core_size), jnp.float32),
        )

    @nn.compact
    def __call__(self, inputs, core_state=(), sample_rng: Optional[jax.Array] = None):
        x = inputs["state"]
        T, B = x.shape[0], x.shape[1]
        x = x.reshape(T * B, -1).astype(self.dtype)
        x = nn.tanh(nn.Dense(self.hidden_size, dtype=self.dtype)(x))
        x = nn.tanh(nn.Dense(self.core_size, dtype=self.dtype)(x))

        if self.use_lstm:
            x = x.reshape(T, B, -1)
            notdone = (~inputs["done"]).astype(jnp.float32)

            class _Core(nn.Module):
                hidden: int

                @nn.compact
                def __call__(self, carry, xs):
                    inp, nd = xs
                    carry = jax.tree_util.tree_map(lambda s: s * nd[:, None], carry)
                    carry, out = nn.OptimizedLSTMCell(self.hidden)(carry, inp)
                    return carry, out

            scan_core = nn.scan(
                _Core,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )(self.core_size)
            core_state, x = scan_core(tuple(core_state), (x.astype(jnp.float32), notdone))
            x = x.reshape(T * B, -1)

        policy_logits = nn.Dense(self.num_actions, dtype=jnp.float32)(x)
        baseline = nn.Dense(1, dtype=jnp.float32)(x)
        out = {
            "policy_logits": policy_logits.reshape(T, B, self.num_actions),
            "baseline": baseline.reshape(T, B),
        }
        if sample_rng is not None:
            out["action"] = jax.random.categorical(sample_rng, out["policy_logits"])
        return out, core_state
