"""Causal Transformer LM with pluggable attention: dense / pallas flash /
ring (sequence-parallel over a mesh axis).

Long-context model surface for the framework's SP capability (the reference
has no attention models at all, SURVEY.md §5.7). Attention selection:

- ``attention="dense"`` — XLA dense (small T, debugging);
- ``attention="flash"`` — pallas blockwise kernel, single chip;
- ``attention="ring"`` — ring attention over the ``sp`` axis of a mesh
  passed at apply time (``model.apply(params, tokens, mesh=mesh)``), for
  sequences longer than one chip's HBM.

Sparse capacity via ``moe_num_experts > 0``: every ``moe_every``-th block
swaps its dense FFN for a :class:`..parallel.moe.SwitchMoE` whose expert
weights shard over an ``ep`` mesh axis (``parallel.moe_shardings``).  The
router's load-balancing aux losses are sowed into the ``losses`` collection:
``logits, col = model.apply(params, tokens, mutable=["losses"])``.

bfloat16 compute, f32 params/logits; pre-LN blocks.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def apply_rotary(x: jax.Array, base: float = 10000.0) -> jax.Array:
    """Rotary position embedding (RoPE, Su et al. 2021) on [B, T, H, D].

    Rotates feature pairs by position-proportional angles so attention scores
    depend on *relative* offsets — the standard long-context choice (no
    learned table capping the usable length, graceful extrapolation).
    Computed in float32 and cast back (bf16 angles visibly distort long-range
    phases).
    """
    B, T, H, D = x.shape
    half = D // 2
    if D % 2:
        raise ValueError(f"rotary needs an even head dim, got {D}")
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)  # [half]
    angles = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Block(nn.Module):
    d_model: int
    num_heads: int
    attention: str
    dtype: Any
    moe_num_experts: int = 0  # 0 = dense FFN; >0 = SwitchMoE FFN (EP-shardable)
    moe_capacity_factor: float = 1.25
    rotary: bool = False

    @nn.compact
    def __call__(self, x, mesh=None):
        B, T, D = x.shape
        H = self.num_heads
        hd = D // H
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        qkv = nn.Dense(3 * D, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv.reshape(B, T, 3 * H, hd), 3, axis=2)
        if self.rotary:
            q, k = apply_rotary(q), apply_rotary(k)

        if self.attention == "ring":
            from ..parallel.ring_attention import ring_attention

            if mesh is None:
                raise ValueError("attention='ring' needs mesh= at apply time")
            att = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        elif self.attention == "flash":
            from ..ops.flash_attention import flash_attention

            att = flash_attention(q, k, v, causal=True)
        else:
            from ..parallel.ring_attention import full_attention

            att = full_attention(q, k, v, causal=True)
        att = att.reshape(B, T, D)
        x = x + nn.Dense(D, dtype=self.dtype, name="proj")(att)

        y = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.moe_num_experts:
            from ..parallel.moe import SwitchMoE

            y, aux = SwitchMoE(
                num_experts=self.moe_num_experts,
                ffn_dim=4 * D,
                capacity_factor=self.moe_capacity_factor,
                dtype=self.dtype,
                residual=False,
                name="moe",
            )(y)
            # Collected by callers via apply(..., mutable=["losses"]) and
            # added to the task loss (Switch Transformer eq. 4 weight ~1e-2).
            self.sow("losses", "moe_aux", aux)
        else:
            y = nn.Dense(4 * D, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(D, dtype=self.dtype)(y)
        return x + y


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 256
    num_heads: int = 4
    num_layers: int = 4
    max_len: int = 8192
    attention: str = "flash"  # dense | flash | ring
    dtype: Any = jnp.bfloat16
    moe_num_experts: int = 0  # >0: MoE FFN on every ``moe_every``-th block
    moe_every: int = 2  # blocks i with i % moe_every == moe_every - 1 use MoE
    moe_capacity_factor: float = 1.25
    pos_embedding: str = "learned"  # learned (table, capped at max_len) | rotary

    @nn.compact
    def __call__(self, tokens: jax.Array, mesh=None) -> jax.Array:
        B, T = tokens.shape
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="embed")(
            tokens
        )
        if self.pos_embedding == "learned":
            x = x + nn.Embed(
                self.max_len, self.d_model, dtype=self.dtype, name="pos"
            )(jnp.arange(T)[None, :])
        elif self.pos_embedding != "rotary":
            raise ValueError(f"unknown pos_embedding {self.pos_embedding!r}")
        for i in range(self.num_layers):
            use_moe = self.moe_num_experts and i % self.moe_every == self.moe_every - 1
            x = Block(
                self.d_model,
                self.num_heads,
                self.attention,
                self.dtype,
                moe_num_experts=self.moe_num_experts if use_moe else 0,
                moe_capacity_factor=self.moe_capacity_factor,
                rotary=self.pos_embedding == "rotary",
                name=f"block{i}",
            )(x, mesh=mesh)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")(
            x.astype(jnp.float32)
        )


def pipeline_lm_apply(
    model: TransformerLM,
    params,
    tokens: jax.Array,
    mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    data_axis: Optional[str] = None,
    circular_repeats: int = 1,
    remat: bool = False,
) -> jax.Array:
    """Apply ``model`` with its transformer blocks run through
    :func:`..parallel.pipeline.pipeline_apply` over the mesh's ``pp`` axis.

    The blocks of a (non-MoE) TransformerLM are structurally identical, so
    their parameters stack into the leading virtual-stage axis the pipeline
    expects; embeddings and the LM head stay outside the pipeline
    (replicated — they are a sliver of the FLOPs).  Differentiable end to
    end: gradients flow back through the schedule into the *per-block*
    leaves of ``params``, so one optimizer tree serves both the pipelined
    and plain paths.  Attention must be "dense" or "flash" (ring attention's
    own collective axis would have to nest inside the pipeline shard_map).

    With ``circular_repeats=v``, the model's ``num_layers`` must be
    ``v * mesh.shape[axis_name]`` and microbatch count a multiple of the pp
    size (see pipeline_apply).
    """
    from ..parallel.pipeline import pipeline_apply

    if model.attention == "ring":
        raise ValueError("pipeline_lm_apply supports dense/flash attention only")
    if model.moe_num_experts:
        raise ValueError(
            "pipeline_lm_apply needs structurally identical blocks (no MoE)"
        )
    B, T = tokens.shape
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by microbatches {num_microbatches}")
    p = params["params"]
    L = model.num_layers

    emb = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    x = emb.apply({"params": p["embed"]}, tokens)
    if model.pos_embedding == "learned":
        pos = nn.Embed(model.max_len, model.d_model, dtype=model.dtype)
        x = x + pos.apply({"params": p["pos"]}, jnp.arange(T)[None, :])
    elif model.pos_embedding != "rotary":
        raise ValueError(f"unknown pos_embedding {model.pos_embedding!r}")

    block = Block(
        model.d_model, model.num_heads, model.attention, model.dtype,
        rotary=model.pos_embedding == "rotary",
    )
    stage_params = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *(p[f"block{i}"] for i in range(L))
    )

    def stage_fn(bp, x):
        return block.apply({"params": bp}, x)

    mb = x.reshape(num_microbatches, B // num_microbatches, T, model.d_model)
    out = pipeline_apply(
        stage_fn,
        stage_params,
        mb,
        mesh,
        axis_name=axis_name,
        data_axis=data_axis,
        circular_repeats=circular_repeats,
        remat=remat,
    )
    x = out.reshape(B, T, model.d_model)
    x = nn.LayerNorm(dtype=jnp.float32).apply({"params": p["ln_f"]}, x)
    head = nn.Dense(model.vocab_size, dtype=jnp.float32)
    return head.apply({"params": p["lm_head"]}, x.astype(jnp.float32))
