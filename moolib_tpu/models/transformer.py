"""Causal Transformer LM with pluggable attention: dense / pallas flash /
ring (sequence-parallel over a mesh axis).

Long-context model surface for the framework's SP capability (the reference
has no attention models at all, SURVEY.md §5.7). Attention selection:

- ``attention="dense"`` — XLA dense (small T, debugging);
- ``attention="flash"`` — pallas blockwise kernel, single chip;
- ``attention="ring"`` — ring attention over the ``sp`` axis of a mesh
  passed at apply time (``model.apply(params, tokens, mesh=mesh)``), for
  sequences longer than one chip's HBM.

Sparse capacity via ``moe_num_experts > 0``: every ``moe_every``-th block
swaps its dense FFN for a :class:`..parallel.moe.SwitchMoE` whose expert
weights shard over an ``ep`` mesh axis (``parallel.moe_shardings``).  The
router's load-balancing aux losses are sowed into the ``losses`` collection:
``logits, col = model.apply(params, tokens, mutable=["losses"])``.

bfloat16 compute, f32 params/logits; pre-LN blocks.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def apply_rotary(x: jax.Array, base: float = 10000.0, offset=0) -> jax.Array:
    """Rotary position embedding (RoPE, Su et al. 2021) on [B, T, H, D].

    Rotates feature pairs by position-proportional angles so attention scores
    depend on *relative* offsets — the standard long-context choice (no
    learned table capping the usable length, graceful extrapolation).
    Computed in float32 and cast back (bf16 angles visibly distort long-range
    phases).  ``offset`` shifts the positions (the cache index during
    autoregressive decoding); it may be a traced scalar, or a traced [B]
    vector when each row sits at its own position (continuous-batching decode
    slots).  The scalar and vector paths compute identical angles for equal
    offsets, so they are bit-exact against each other.
    """
    B, T, H, D = x.shape
    half = D // 2
    if D % 2:
        raise ValueError(f"rotary needs an even head dim, got {D}")
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)  # [half]
    off = jnp.asarray(offset)
    if off.ndim == 0:
        positions = off + jnp.arange(T, dtype=jnp.float32)
        angles = positions[:, None] * freqs[None, :]  # [T, half]
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        positions = off[:, None] + jnp.arange(T, dtype=jnp.float32)[None, :]
        angles = positions[..., None] * freqs  # [B, T, half]
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


REMAT_POLICIES = ("full", "dots", "dots_no_batch")


def _remat_policy(name: str):
    """Resolve a TransformerLM.remat_policy name to a jax.checkpoint policy
    (None = save nothing, jax.checkpoint's default)."""
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"remat_policy must be one of {'|'.join(REMAT_POLICIES)}, got {name!r}"
        )
    if name == "full":
        return None
    return {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    }[name]


class Block(nn.Module):
    d_model: int
    num_heads: int
    attention: str
    dtype: Any
    moe_num_experts: int = 0  # 0 = dense FFN; >0 = SwitchMoE FFN (EP-shardable)
    moe_capacity_factor: float = 1.25
    rotary: bool = False
    decode: bool = False  # single-token steps against a KV cache (generation)
    max_len: int = 8192  # cache capacity in decode mode
    collect_kv: bool = False  # sow K/V into a "kv" collection (prefill)
    num_kv_heads: Optional[int] = None  # GQA: KV heads < query heads
    # Paged KV cache (continuous-batching decode; see ops.paged_attention):
    # >0 switches the decode cache from dense [B, max_len, Hk, hd] to a
    # shared block pool [kv_num_blocks, kv_block_size, Hk, hd] addressed via
    # the PagedState passed at apply time.
    kv_num_blocks: int = 0
    kv_block_size: int = 16

    @nn.compact
    def __call__(self, x, mesh=None, paged=None):
        B, T, D = x.shape
        H = self.num_heads
        hd = D // H
        # Grouped-query attention (Ainslie et al. 2023): Hk KV heads are
        # shared by groups of H/Hk query heads — the KV cache (the HBM
        # bottleneck at serve time) shrinks by that factor.  Hk == H is
        # exactly multi-head attention (identical params and math).
        Hk = self.num_kv_heads or H
        if H % Hk:
            raise ValueError(f"num_heads={H} must be divisible by num_kv_heads={Hk}")
        group = H // Hk
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        qkv = nn.Dense((H + 2 * Hk) * hd, dtype=self.dtype, name="qkv")(y)
        qkv = qkv.reshape(B, T, H + 2 * Hk, hd)
        q, k, v = qkv[:, :, :H], qkv[:, :, H : H + Hk], qkv[:, :, H + Hk :]

        if self.decode:
            # Autoregressive step: x is [B, 1, D]; append this position's
            # K/V to the cache and attend over everything cached so far.
            # The cache holds Hk heads; query heads address their group's
            # KV head through a grouped einsum — no repeat materializes.
            if T != 1:
                raise ValueError(f"decode mode steps one token at a time, got T={T}")
            from ..ops.paged_attention import (
                gathered_decode_attention,
                paged_attention,
                paged_kv_write,
            )

            if self.kv_num_blocks:
                # Paged layout: K/V live in a pool shared by all decode
                # slots; each slot addresses its blocks through the block
                # table in ``paged``.  Same math as the dense branch below
                # (both call gathered_decode_attention), different storage.
                if paged is None:
                    raise ValueError("kv_num_blocks > 0 needs paged= at apply time")
                pk = self.variable(
                    "cache", "pool_k", jnp.zeros,
                    (self.kv_num_blocks, self.kv_block_size, Hk, hd), self.dtype,
                )
                pv = self.variable(
                    "cache", "pool_v", jnp.zeros,
                    (self.kv_num_blocks, self.kv_block_size, Hk, hd), self.dtype,
                )
                t = paged.lengths
                if self.rotary:
                    q = apply_rotary(q, offset=t)
                    k = apply_rotary(k, offset=t)
                pk.value = paged_kv_write(
                    pk.value, k[:, 0], paged.block_tables, t, paged.active
                )
                pv.value = paged_kv_write(
                    pv.value, v[:, 0], paged.block_tables, t, paged.active
                )
                att = paged_attention(
                    q, pk.value, pv.value, paged.block_tables, t
                ).astype(x.dtype)
            else:
                ck = self.variable(
                    "cache", "k", jnp.zeros, (B, self.max_len, Hk, hd), self.dtype
                )
                cv = self.variable(
                    "cache", "v", jnp.zeros, (B, self.max_len, Hk, hd), self.dtype
                )
                idx = self.variable(
                    "cache", "idx", lambda: jnp.zeros((), jnp.int32)
                )
                t = idx.value
                if self.rotary:
                    q = apply_rotary(q, offset=t)
                    k = apply_rotary(k, offset=t)
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(self.dtype), (0, t, 0, 0)
                )
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(self.dtype), (0, t, 0, 0)
                )
                idx.value = t + 1
                att = gathered_decode_attention(q, ck.value, cv.value, t).astype(
                    x.dtype
                )
        else:
            if self.rotary:
                q, k = apply_rotary(q), apply_rotary(k)
            if self.collect_kv:
                # One-pass prefill: generate() reads these to seed the cache
                # (unrepeated — the cache stays Hk heads).
                self.sow("kv", "k", k.astype(self.dtype))
                self.sow("kv", "v", v.astype(self.dtype))
            if group > 1:
                # Training/prefill path: the attention kernels take equal
                # head counts — repeat KV across each group (transient; the
                # cache and the params stay at Hk heads).
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            if self.attention == "ring":
                from ..parallel.ring_attention import ring_attention

                if mesh is None:
                    raise ValueError("attention='ring' needs mesh= at apply time")
                att = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
            elif self.attention == "flash":
                from ..ops.flash_attention import flash_attention

                att = flash_attention(q, k, v, causal=True)
            else:
                from ..parallel.ring_attention import full_attention

                att = full_attention(q, k, v, causal=True)
        att = att.reshape(B, T, D)
        x = x + nn.Dense(D, dtype=self.dtype, name="proj")(att)

        y = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.moe_num_experts:
            from ..parallel.moe import SwitchMoE

            y, aux = SwitchMoE(
                num_experts=self.moe_num_experts,
                ffn_dim=4 * D,
                capacity_factor=self.moe_capacity_factor,
                dtype=self.dtype,
                residual=False,
                name="moe",
            )(y)
            # Collected by callers via apply(..., mutable=["losses"]) and
            # added to the task loss (Switch Transformer eq. 4 weight ~1e-2).
            self.sow("losses", "moe_aux", aux)
        else:
            y = nn.Dense(4 * D, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(D, dtype=self.dtype)(y)
        return x + y


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 256
    num_heads: int = 4
    num_kv_heads: Optional[int] = None  # GQA (None = num_heads: plain MHA)
    num_layers: int = 4
    max_len: int = 8192
    attention: str = "flash"  # dense | flash | ring
    dtype: Any = jnp.bfloat16
    moe_num_experts: int = 0  # >0: MoE FFN on every ``moe_every``-th block
    moe_every: int = 2  # blocks i with i % moe_every == moe_every - 1 use MoE
    moe_capacity_factor: float = 1.25
    pos_embedding: str = "learned"  # learned (table, capped at max_len) | rotary
    decode: bool = False  # single-token KV-cache steps (see generate())
    collect_kv: bool = False  # sow per-block K/V (generate()'s prefill)
    # Paged decode (engine.ContinuousBatchingEngine): >0 makes every block's
    # cache a shared pool addressed by the PagedState passed via paged=.
    kv_num_blocks: int = 0
    kv_block_size: int = 16
    remat: bool = False  # checkpoint each block: O(L) -> O(1) activations
    # What the per-block checkpoint SAVES (only meaningful with remat=True):
    #   "full"          — save nothing: every op recomputed in the backward
    #                     (max memory saving, ~1/3 extra FLOPs)
    #   "dots"          — save every dot/matmul output, recompute only the
    #                     cheap elementwise/norm work: the MXU never re-runs,
    #                     at higher memory than "full" (pallas flash calls
    #                     are not dots, so attention is still recomputed —
    #                     its own kernel already keeps residuals O(T))
    #   "dots_no_batch" — like "dots" but only matmuls with no batch dims
    #                     (weight@activation, not activation@activation)
    remat_policy: str = "full"

    @nn.compact
    def __call__(
        self, tokens: jax.Array, mesh=None, return_features: bool = False,
        paged=None,
    ) -> jax.Array:
        """Logits [B, T, V] — or pre-head features [B, T, D] with
        ``return_features=True``, for ``ops.xent.lm_head_xent``'s chunked
        loss (the lm_head params still come from the same init: flax only
        materializes params on the default path, and ``apply`` ignores the
        unused head when features are requested)."""
        B, T = tokens.shape
        # Validate even when remat/decode makes the policy a no-op: bench
        # rows are keyed by this string, so a typo must never run silently.
        _remat_policy(self.remat_policy)
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="embed")(
            tokens
        )
        if self.pos_embedding == "learned":
            pos_idx = jnp.arange(T)[None, :]
            if self.decode and paged is not None:
                # Paged decode: each slot sits at its own position — the
                # per-slot lengths ARE the position counter.
                pos_idx = pos_idx + paged.lengths[:, None]
            elif self.decode:
                # The LM owns its position counter (how many tokens have
                # been decoded) rather than peeking at a child block's cache.
                ctr = self.variable(
                    "cache", "pos_idx", lambda: jnp.zeros((), jnp.int32)
                )
                pos_idx = pos_idx + ctr.value
                ctr.value = ctr.value + T
            x = x + nn.Embed(
                self.max_len, self.d_model, dtype=self.dtype, name="pos"
            )(pos_idx)
        elif self.pos_embedding != "rotary":
            raise ValueError(f"unknown pos_embedding {self.pos_embedding!r}")
        # remat trades ~1/3 extra FLOPs for O(1)-in-depth activation memory
        # (HBM is the usual TPU bottleneck): each block's activations are
        # recomputed during the backward instead of stored.  Bigger batches
        # then fit at long T, which is how lm_bench pushes MFU.  mesh is a
        # static argument (index 2 counting self), not a traced operand.
        if self.remat and not self.decode:
            block_cls = nn.remat(
                Block, static_argnums=(2,), policy=_remat_policy(self.remat_policy)
            )
        else:
            block_cls = Block
        for i in range(self.num_layers):
            use_moe = self.moe_num_experts and i % self.moe_every == self.moe_every - 1
            block = block_cls(
                self.d_model,
                self.num_heads,
                self.attention,
                self.dtype,
                moe_num_experts=self.moe_num_experts if use_moe else 0,
                moe_capacity_factor=self.moe_capacity_factor,
                rotary=self.pos_embedding == "rotary",
                decode=self.decode,
                max_len=self.max_len,
                collect_kv=self.collect_kv,
                num_kv_heads=self.num_kv_heads,
                kv_num_blocks=self.kv_num_blocks,
                kv_block_size=self.kv_block_size,
                name=f"block{i}",
            )
            # paged stays out of the remat-wrapped call (remat only wraps
            # the non-decode path, where paged is always None).
            x = block(x, mesh) if paged is None else block(x, mesh, paged)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        head = nn.Dense(self.vocab_size, dtype=jnp.float32, name="lm_head")
        if return_features:
            if self.is_initializing():
                # Materialize the head's params even on the features path so
                # init(..., return_features=True) yields the same tree as the
                # default path (lm_head_xent reads params["params"]["lm_head"]).
                head(x.astype(jnp.float32)[:, :1])
            return x.astype(jnp.float32)
        return head(x.astype(jnp.float32))


def generate(
    model: TransformerLM,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive sampling with a per-block KV cache.

    ``prompt`` is [B, Tp] int32; returns [B, Tp + max_new_tokens] with the
    continuation appended.  Prefill is ONE teacher-forced forward over the
    prompt (each block sows its K/V, which seed the cache); each generated
    token is then a single-position step against the cached K/V — O(T) per
    token instead of O(T²) re-forwarding (the flax ``decode`` pattern, the
    cache collection carried through a scan).  ``temperature=0`` is greedy
    argmax; otherwise softmax sampling with ``rng``.
    """
    B, Tp = prompt.shape
    if Tp + max_new_tokens > model.max_len:
        raise ValueError(
            f"prompt + max_new_tokens = {Tp + max_new_tokens} exceeds the "
            f"cache capacity max_len={model.max_len}"
        )
    if model.moe_num_experts:
        # Per-step Switch capacity is computed over B tokens, not B*T, so
        # cached decoding would drop different tokens than the training
        # forward — refuse rather than silently diverge.
        raise ValueError("generate() does not support MoE models yet")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 needs an explicit rng key")
    dec = TransformerLM(
        vocab_size=model.vocab_size,
        d_model=model.d_model,
        num_heads=model.num_heads,
        num_kv_heads=model.num_kv_heads,
        num_layers=model.num_layers,
        max_len=model.max_len,
        attention="dense",  # unused in decode steps (cached attention)
        dtype=model.dtype,
        moe_num_experts=model.moe_num_experts,
        moe_every=model.moe_every,
        moe_capacity_factor=model.moe_capacity_factor,
        pos_embedding=model.pos_embedding,
        decode=True,
    )
    pdict = {"params": params["params"]}

    def step(cache, tok):
        logits, upd = dec.apply(
            {**pdict, "cache": cache}, tok[:, None], mutable=["cache"]
        )
        return upd["cache"], logits[:, 0]

    # Prefill in ONE teacher-forced forward over the whole prompt: the
    # full model sows every block's K/V (collect_kv) and the cache is
    # assembled from them — not Tp sequential single-token steps.
    full = TransformerLM(
        vocab_size=model.vocab_size,
        d_model=model.d_model,
        num_heads=model.num_heads,
        num_kv_heads=model.num_kv_heads,
        num_layers=model.num_layers,
        max_len=model.max_len,
        # Prefill rides the model's own attention kind, so long prompts go
        # through the flash kernel instead of a Tp² dense score matrix.
        # ring needs a mesh at apply time (generate() takes none); its
        # single-chip equivalent is flash.
        attention="flash" if model.attention == "ring" else model.attention,
        dtype=model.dtype,
        pos_embedding=model.pos_embedding,
        collect_kv=True,
    )
    full_logits, col = full.apply(pdict, prompt, mutable=["kv"])
    last_logits = full_logits[:, -1]
    pad = model.max_len - Tp
    cache = {}
    for i in range(model.num_layers):
        kv = col["kv"][f"block{i}"]
        cache[f"block{i}"] = {
            "k": jnp.pad(kv["k"][0], ((0, 0), (0, pad), (0, 0), (0, 0))),
            "v": jnp.pad(kv["v"][0], ((0, 0), (0, pad), (0, 0), (0, 0))),
            "idx": jnp.asarray(Tp, jnp.int32),
        }
    if model.pos_embedding == "learned":
        cache["pos_idx"] = jnp.asarray(Tp, jnp.int32)

    if rng is None:
        rng = jax.random.key(0)  # unused: greedy path (temperature == 0)

    def gen_step(carry, _):
        cache, logits, rng = carry
        if temperature == 0.0:
            tok = jnp.argmax(logits, axis=-1)
        else:
            rng, sub = jax.random.split(rng)
            tok = jax.random.categorical(sub, logits / temperature, axis=-1)
        cache, logits = step(cache, tok)
        return (cache, logits, rng), tok

    (_, _, _), new_toks = jax.lax.scan(
        gen_step, (cache, last_logits, rng), None, length=max_new_tokens
    )
    return jnp.concatenate([prompt, new_toks.T.astype(prompt.dtype)], axis=1)


def sharded_generator(
    model: TransformerLM,
    params,
    max_new_tokens: int,
    mesh,
    params_sharding=None,
    temperature: float = 0.0,
    sample: bool = False,
):
    """Build a REUSABLE tensor-parallel generation function: the whole of
    :func:`generate` (flash prefill + KV-cache decode scan) jitted once over
    ``mesh`` with the params sharded — serving models larger than one chip's
    HBM, with the jit cache hit on every subsequent call.

    ``params_sharding`` defaults to ``parallel.auto_shardings`` (TP on the
    last axis of big kernels + FSDP), the same tree the training step uses,
    so a trained sharded model serves without a resharding hop.  XLA
    propagates the sharding through the per-block KV caches (heads follow
    the attention kernels' TP axis) and inserts the decode-time collectives.
    Prompt and output are replicated (the batch is tiny at serve time).

    Returns ``fn(params, prompt)`` (greedy) or ``fn(params, prompt, rng)``
    when ``sample=True`` (softmax sampling at ``temperature``).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.train import auto_shardings

    if params_sharding is None:
        params_sharding = auto_shardings(params, mesh)
    rep = NamedSharding(mesh, PartitionSpec())
    n_rng = 1 if sample else 0
    return jax.jit(
        lambda p, t, *r: generate(model, p, t, max_new_tokens, temperature, *r),
        in_shardings=(params_sharding, rep) + (rep,) * n_rng,
        out_shardings=rep,
    )


def generate_sharded(
    model: TransformerLM,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    mesh,
    params_sharding=None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """One-shot form of :func:`sharded_generator` (repeated callers should
    build the generator once and reuse it — each call here re-jits)."""
    fn = sharded_generator(
        model, params, max_new_tokens, mesh, params_sharding, temperature,
        sample=rng is not None,
    )
    return fn(params, prompt, rng) if rng is not None else fn(params, prompt)


def pipeline_lm_apply(
    model: TransformerLM,
    params,
    tokens: jax.Array,
    mesh,
    num_microbatches: int,
    axis_name: str = "pp",
    data_axis: Optional[str] = None,
    circular_repeats: int = 1,
    remat: bool = False,
    remat_policy: str = "full",
) -> jax.Array:
    """Apply ``model`` with its transformer blocks run through
    :func:`..parallel.pipeline.pipeline_apply` over the mesh's ``pp`` axis.

    The blocks of a (non-MoE) TransformerLM are structurally identical, so
    their parameters stack into the leading virtual-stage axis the pipeline
    expects; embeddings and the LM head stay outside the pipeline
    (replicated — they are a sliver of the FLOPs).  Differentiable end to
    end: gradients flow back through the schedule into the *per-block*
    leaves of ``params``, so one optimizer tree serves both the pipelined
    and plain paths.  Attention must be "dense" or "flash" (ring attention's
    own collective axis would have to nest inside the pipeline shard_map).

    With ``circular_repeats=v``, the model's ``num_layers`` must be
    ``v * mesh.shape[axis_name]`` and microbatch count a multiple of the pp
    size (see pipeline_apply).
    """
    from ..parallel.pipeline import pipeline_apply

    if model.attention == "ring":
        raise ValueError("pipeline_lm_apply supports dense/flash attention only")
    if model.moe_num_experts:
        raise ValueError(
            "pipeline_lm_apply needs structurally identical blocks (no MoE)"
        )
    B, T = tokens.shape
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by microbatches {num_microbatches}")
    p = params["params"]
    L = model.num_layers

    emb = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype)
    x = emb.apply({"params": p["embed"]}, tokens)
    if model.pos_embedding == "learned":
        pos = nn.Embed(model.max_len, model.d_model, dtype=model.dtype)
        x = x + pos.apply({"params": p["pos"]}, jnp.arange(T)[None, :])
    elif model.pos_embedding != "rotary":
        raise ValueError(f"unknown pos_embedding {model.pos_embedding!r}")

    block = Block(
        model.d_model, model.num_heads, model.attention, model.dtype,
        rotary=model.pos_embedding == "rotary",
        num_kv_heads=model.num_kv_heads,
    )
    stage_params = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *(p[f"block{i}"] for i in range(L))
    )

    def stage_fn(bp, x):
        return block.apply({"params": bp}, x)

    mb = x.reshape(num_microbatches, B // num_microbatches, T, model.d_model)
    out = pipeline_apply(
        stage_fn,
        stage_params,
        mb,
        mesh,
        axis_name=axis_name,
        data_axis=data_axis,
        circular_repeats=circular_repeats,
        remat=remat,
        remat_policy=_remat_policy(remat_policy),
    )
    x = out.reshape(B, T, model.d_model)
    x = nn.LayerNorm(dtype=jnp.float32).apply({"params": p["ln_f"]}, x)
    head = nn.Dense(model.vocab_size, dtype=jnp.float32)
    return head.apply({"params": p["lm_head"]}, x.astype(jnp.float32))
