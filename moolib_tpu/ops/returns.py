"""Return/advantage estimators: n-step discounted returns and GAE.

The n-step return matches the reference A2C loss inputs
(``examples/a2c.py:121-164``); GAE is provided for the recurrent-PPO family
(BASELINE.json config list).  All are ``lax.scan`` formulations.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def discounted_returns(
    rewards: jax.Array, discounts: jax.Array, bootstrap_value: jax.Array
) -> jax.Array:
    """R_t = r_t + gamma_t * R_{t+1}, time-major [T, B]."""

    def body(acc, xs):
        r_t, d_t = xs
        acc = r_t + d_t * acc
        return acc, acc

    _, out = jax.lax.scan(body, bootstrap_value, (rewards, discounts), reverse=True)
    return out


def generalized_advantage_estimation(
    rewards: jax.Array,
    values: jax.Array,
    discounts: jax.Array,
    bootstrap_value: jax.Array,
    lambda_: float = 0.95,
):
    """GAE(lambda); returns (advantages, value_targets), time-major [T, B]."""
    values_t1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = rewards + discounts * values_t1 - values

    def body(acc, xs):
        delta_t, d_t = xs
        acc = delta_t + d_t * lambda_ * acc
        return acc, acc

    _, advantages = jax.lax.scan(
        body, jnp.zeros_like(bootstrap_value), (deltas, discounts), reverse=True
    )
    return jax.lax.stop_gradient(advantages), jax.lax.stop_gradient(advantages + values)


def entropy_loss(logits: jax.Array) -> jax.Array:
    """Negative mean policy entropy (minimized => maximises entropy)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    return jnp.mean(jnp.sum(p * logp, axis=-1))


def softmax_cross_entropy(logits: jax.Array, actions: jax.Array) -> jax.Array:
    """-log pi(a|s), elementwise (policy-gradient building block)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, actions[..., None], axis=-1).squeeze(-1)
