"""Numerical ops: v-trace, returns/advantages, losses — all jit-safe."""

from . import returns, vtrace, xent  # noqa: F401
from .returns import (  # noqa: F401
    discounted_returns,
    entropy_loss,
    generalized_advantage_estimation,
    softmax_cross_entropy,
)
from .xent import chunked_softmax_xent, lm_head_xent  # noqa: F401
