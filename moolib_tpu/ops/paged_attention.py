"""Paged KV-cache decode attention (block-table gather).

The serving engine's KV layout: instead of one dense ``[B, max_len, Hk, hd]``
cache per sequence, K/V live in a shared device-resident pool of fixed-size
token blocks ``[num_blocks, block_size, Hk, hd]`` and each decode *slot* owns
an int32 row of block ids (its block table).  Attention gathers the slot's
blocks back into a contiguous context and runs the exact same grouped-query
math as the dense ``decode=True`` path in ``models.transformer.Block`` — the
shared function :func:`gathered_decode_attention` is called by BOTH paths, so
paged decode is bit-identical to the dense cache whenever the gathered context
length equals the dense ``max_len`` (tests/test_paged_attention.py pins this).

Why a gather kernel and not a fused pallas kernel: decode attention at serve
batch sizes is bandwidth-bound on the KV pool read either way; the XLA gather
lowers to the same HBM traffic on TPU and runs unmodified on CPU, which is
where tier-1 CI executes.  The layout (pool + block tables + per-slot
lengths) is exactly what a fused kernel would take, so one can slot in later
without touching the engine.

Block id 0 is the *null block*: never handed out by the allocator, and the
write path redirects inactive slots' scatters at it, so a fixed-shape jitted
step over all S slots never branches on occupancy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


class PagedState(NamedTuple):
    """Per-slot decode state threaded through a paged decode step.

    block_tables: int32 [S, max_blocks_per_seq] — pool block ids per slot
        (unused tail entries hold 0, the null block).
    lengths: int32 [S] — tokens already in the cache for each slot; the
        current step writes at position ``lengths`` and attends over
        ``<= lengths`` (the just-written token included).
    active: bool [S] — occupied slots.  Inactive slots still execute the
        step (fixed shape); their writes land in the null block and their
        outputs are ignored by the engine.
    """

    block_tables: jax.Array
    lengths: jax.Array
    active: jax.Array


def gathered_decode_attention(q, k_ctx, v_ctx, t):
    """Single-position grouped-query attention over a gathered context.

    q: [B, 1, H, hd]; k_ctx/v_ctx: [B, T_ctx, Hk, hd] (any dtype — cast to
    f32 here, like the dense path); t: scalar or [B] int — attend over
    positions ``<= t`` (everything past t contributes exactly 0: the -1e30
    masked scores underflow to 0 in the f32 softmax).  This is the one
    definition of the decode-attention math; the dense ``decode=True`` branch
    and the paged gather path both call it, which is what makes the two
    cache layouts bit-exact against each other.
    """
    B, T, H, hd = q.shape
    Hk = k_ctx.shape[2]
    group = H // Hk
    T_ctx = k_ctx.shape[1]
    scale = hd**-0.5
    qg = q.reshape(B, T, Hk, group, hd)
    scores = (
        jnp.einsum(
            "bqhgd,bkhd->bhgqk",
            qg.astype(jnp.float32),
            k_ctx.astype(jnp.float32),
        )
        * scale
    )
    t = jnp.asarray(t)
    pos = jnp.arange(T_ctx)
    if t.ndim == 0:
        mask = pos[None, None, None, None, :] <= t
    else:
        mask = pos[None, None, None, None, :] <= t[:, None, None, None, None]
    scores = jnp.where(mask, scores, _NEG_INF)
    p_att = jax.nn.softmax(scores, axis=-1)
    att = jnp.einsum("bhgqk,bkhd->bqhgd", p_att, v_ctx.astype(jnp.float32))
    return att.reshape(B, T, H, hd).astype(q.dtype)


def paged_kv_write(pool, x, block_tables, lengths, active):
    """Scatter one new K (or V) row per slot into the block pool, in place.

    pool: [num_blocks, block_size, Hk, hd]; x: [S, Hk, hd] (this step's K or
    V at position ``lengths``); block_tables/lengths/active as in
    :class:`PagedState`.  Inactive slots write to the null block 0 — the
    allocator never hands it out, so the garbage is harmless and the op
    keeps a fixed shape.  Used under donation: ``pool.at[...].set`` on a
    donated buffer updates HBM in place (no copy at join/retire).
    """
    bs = pool.shape[1]
    blk = jnp.take_along_axis(block_tables, (lengths // bs)[:, None], axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)
    off = lengths % bs
    return pool.at[blk, off].set(x.astype(pool.dtype))


def paged_gather(pool, block_tables):
    """Gather each slot's blocks into a contiguous [S, T_ctx, Hk, hd] context
    (T_ctx = max_blocks_per_seq * block_size).  Positions past a slot's
    length are stale pool contents; the attention mask zeroes them."""
    S, nb = block_tables.shape
    ctx = pool[block_tables]  # [S, nb, bs, Hk, hd]
    return ctx.reshape(S, nb * pool.shape[1], *pool.shape[2:])


def paged_attention(q, pool_k, pool_v, block_tables, lengths):
    """Decode attention against a paged KV pool: gather, then the shared
    grouped-query math.  q: [S, 1, H, hd]; returns [S, 1, H, hd]."""
    k_ctx = paged_gather(pool_k, block_tables)
    v_ctx = paged_gather(pool_v, block_tables)
    return gathered_decode_attention(q, k_ctx, v_ctx, lengths)
