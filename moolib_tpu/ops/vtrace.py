"""V-trace off-policy actor-critic targets (IMPALA), jax-native.

Same math as the reference's ``examples/common/vtrace.py:50-242`` (itself from
deepmind/scalable_agent, Espeholt et al. 2018), re-expressed as a
``lax.scan`` over the time axis — the natural XLA formulation (static shapes,
no python loop, fuses with the surrounding jitted loss).

Conventions: time-major tensors ``[T, B]`` (``[T, B, A]`` for logits),
``bootstrap_value`` ``[B]``.  All functions are jit/vmap/grad-safe.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class VTraceReturns(NamedTuple):
    vs: jax.Array  # [T, B] value targets
    pg_advantages: jax.Array  # [T, B] policy-gradient advantages


class VTraceFromLogitsReturns(NamedTuple):
    vs: jax.Array
    pg_advantages: jax.Array
    log_rhos: jax.Array
    behavior_action_log_probs: jax.Array
    target_action_log_probs: jax.Array


def action_log_probs(policy_logits: jax.Array, actions: jax.Array) -> jax.Array:
    """log pi(a|s) from logits [..., A] and integer actions [...]."""
    logp = jax.nn.log_softmax(policy_logits, axis=-1)
    return jnp.take_along_axis(logp, actions[..., None], axis=-1).squeeze(-1)


def from_importance_weights(
    log_rhos: jax.Array,
    discounts: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    clip_rho_threshold: float = 1.0,
    clip_pg_rho_threshold: float = 1.0,
    lambda_: float = 1.0,
) -> VTraceReturns:
    """Core v-trace recursion (reference ``from_importance_weights``)."""
    rhos = jnp.exp(log_rhos)
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
    cs = lambda_ * jnp.minimum(1.0, rhos)
    values_t_plus_1 = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)

    def body(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v_xs = jax.lax.scan(
        body,
        jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs),
        reverse=True,
    )
    vs = values + vs_minus_v_xs

    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    clipped_pg_rhos = jnp.minimum(clip_pg_rho_threshold, rhos)
    pg_advantages = clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 - values)
    # Targets are constants wrt the learner parameters.
    return VTraceReturns(
        vs=jax.lax.stop_gradient(vs), pg_advantages=jax.lax.stop_gradient(pg_advantages)
    )


def from_logits(
    behavior_policy_logits: jax.Array,
    target_policy_logits: jax.Array,
    actions: jax.Array,
    discounts: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    bootstrap_value: jax.Array,
    clip_rho_threshold: float = 1.0,
    clip_pg_rho_threshold: float = 1.0,
    lambda_: float = 1.0,
) -> VTraceFromLogitsReturns:
    """V-trace from behavior/target logits (reference ``from_logits``)."""
    behavior_log_probs = action_log_probs(behavior_policy_logits, actions)
    target_log_probs = action_log_probs(target_policy_logits, actions)
    log_rhos = target_log_probs - behavior_log_probs
    vt = from_importance_weights(
        log_rhos,
        discounts,
        rewards,
        values,
        bootstrap_value,
        clip_rho_threshold,
        clip_pg_rho_threshold,
        lambda_,
    )
    return VTraceFromLogitsReturns(
        vs=vt.vs,
        pg_advantages=vt.pg_advantages,
        log_rhos=log_rhos,
        behavior_action_log_probs=behavior_log_probs,
        target_action_log_probs=target_log_probs,
    )
