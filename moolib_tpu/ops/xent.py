"""Chunked softmax cross-entropy: the LM head without materialized logits.

``TransformerLM``'s head projects to vocab-size logits; at the bench scale
(B=16, T=1024, V=32768, f32) the logits tensor alone is ~2 GB, and the
naive ``log_softmax`` loss makes XLA stream it to HBM at least twice more
(backward residuals) — pure bandwidth, zero MXU work.  This op runs the
projection blockwise over the vocab axis inside a ``lax.scan`` whose body
is ``jax.checkpoint``ed: the forward keeps only three [N] row statistics
(running max, rescaled sum-of-exp, label logit) per chunk step, and the
backward recomputes each chunk's logits on the fly.  Per-token head FLOPs
go from 6·D·V to 8·D·V (one recompute pass) while the [N, V] tensor never
exists — the classic memory-for-FLOPs trade that wins on TPU, same family
as ``remat=True`` on the blocks and the flash-attention kernels.

The reference framework has no LM/loss machinery at all (its models stop
at policy/value heads, SURVEY.md §2.2); this extends the long-context side
the same way flash attention does — TPU-idiomatic from the start, via
scan + checkpoint rather than a hand-scheduled kernel, because the blocked
matmul is already MXU-shaped and XLA fuses the elementwise tail.

Numerics: logits are computed in f32 (``preferred_element_type``) from
inputs in their stored dtype, the online logsumexp carries are f32, and
the result equals the naive ``log_softmax`` loss to f32 roundoff (pinned
by tests/test_xent.py, including through ``jax.grad``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def chunked_softmax_xent(
    h: jax.Array,
    w: jax.Array,
    b: Optional[jax.Array],
    labels: jax.Array,
    chunk_size: int = 4096,
    compute_dtype=None,
) -> jax.Array:
    """Mean negative log-likelihood of ``labels`` under ``softmax(h @ w + b)``.

    h: [N, D], w: [D, V], b: [V] or None, labels: [N] int.  Returns a f32
    scalar.  ``chunk_size`` bounds the live logits block to
    [N, chunk_size]; a vocab the chunk doesn't divide gets one extra
    static-width tail block (never a padded copy of w).

    ``compute_dtype`` casts the matmul *inputs* (e.g. ``jnp.bfloat16``;
    accumulation stays f32 via ``preferred_element_type``).  On TPU an f32
    matmul runs multi-pass at a fraction of bf16 throughput, and at bench
    scale the head is a third of the whole train step — bf16 inputs are
    the standard production trade (logsumexp statistics stay f32).
    Default ``None`` keeps the inputs' own dtype (f32 parity with the
    materialized ``log_softmax`` path).
    """
    n, d = h.shape
    v = w.shape[1]
    chunk = int(min(chunk_size, v))
    if b is None:
        b = jnp.zeros((v,), jnp.float32)
    labels = labels.astype(jnp.int32)
    if compute_dtype is not None:
        h = h.astype(compute_dtype)

    def update(carry, wc, bc, base, width):
        """Fold one [N, width] logits block into the running statistics."""
        m, s, lab = carry
        if compute_dtype is not None:
            wc = wc.astype(compute_dtype)  # per chunk: no full-w copy
        logits = (
            jnp.dot(h, wc, preferred_element_type=jnp.float32)
            + bc.astype(jnp.float32)[None, :]
        )  # the only live logits block
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]
        ).sum(axis=-1)
        off = labels - base
        hit = (off >= 0) & (off < width)
        picked = jnp.take_along_axis(
            logits, jnp.clip(off, 0, width - 1)[:, None], axis=1
        )[:, 0]
        return m_new, s, lab + jnp.where(hit, picked, 0.0)

    carry = (
        jnp.full((n,), _NEG, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    # Full-width blocks ride a scan; a ragged tail (chunk not dividing V)
    # is one extra static-width block — no padded copy of the whole [D, V]
    # weight (which would double head-weight traffic for, say, V=50257).
    n_full = v // chunk

    def body(carry, i):
        wc = lax.dynamic_slice_in_dim(w, i * chunk, chunk, axis=1)
        bc = lax.dynamic_slice_in_dim(b, i * chunk, chunk)
        return update(carry, wc, bc, i * chunk, chunk), None

    # checkpoint: scan would otherwise stash every chunk's [N, chunk] logits
    # as backward residuals — re-materializing exactly the tensor this op
    # exists to avoid.  With it, only the [N] carries survive the forward.
    # prevent_cse=False: safe (and documented as the right setting) inside
    # scan, and it drops the optimization barriers that would block XLA
    # from fusing the logsumexp tail into the blocked matmul.
    if n_full:
        carry, _ = lax.scan(
            jax.checkpoint(body, prevent_cse=False), carry, jnp.arange(n_full)
        )
    if v % chunk:
        tail = jax.checkpoint(
            lambda c: update(
                c, w[:, n_full * chunk:], b[n_full * chunk:],
                n_full * chunk, v - n_full * chunk,
            ),
            prevent_cse=False,
        )
        carry = tail(carry)
    m, s, lab = carry
    return ((m + jnp.log(s)) - lab).mean()


def lm_head_xent(
    model,
    params,
    tokens: jax.Array,
    chunk_size: int = 4096,
    mesh=None,
    compute_dtype=None,
) -> jax.Array:
    """Next-token NLL for a ``TransformerLM`` without materialized logits.

    Runs the backbone (``return_features=True``), then the chunked head on
    the flattened [B*(T-1), D] features against the shifted tokens, reading
    the same ``lm_head`` parameters ``model.apply`` would use — one init,
    either loss path.
    """
    feats = model.apply(params, tokens, mesh, return_features=True)
    head = params["params"]["lm_head"]
    b, t, dm = feats.shape
    return chunked_softmax_xent(
        feats[:, :-1].reshape(b * (t - 1), dm).astype(jnp.float32),
        head["kernel"].astype(jnp.float32),
        head["bias"].astype(jnp.float32),
        tokens[:, 1:].reshape(-1),
        chunk_size=chunk_size,
        compute_dtype=compute_dtype,
    )


def naive_softmax_xent(h, w, b, labels):
    """The materialized-logits loss the chunked op replaces (test oracle)."""
    logits = jnp.dot(h, w, preferred_element_type=jnp.float32)
    if b is not None:
        logits = logits + b.astype(jnp.float32)[None, :]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()
