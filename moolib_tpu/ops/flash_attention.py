"""Pallas TPU flash attention (single-chip blockwise attention).

The single-chip complement of ``moolib_tpu.parallel.ring_attention``: scores
never materialize in HBM — K/V stream through VMEM in blocks while a running
(max, sum, accumulator) triple folds the softmax (same math as the ring
kernel, here over the *local* sequence).  Written with ``pl.pallas_call``
grid (batch*heads, q-blocks, kv-blocks): the kv axis is innermost so the
output block revisits and the scratch accumulators carry across iterations
(standard TPU pallas accumulation pattern).

The reference framework has no attention at all (SURVEY.md §5.7) — this is
new TPU-idiomatic capability for the long-context side of the framework.

Layout [B, T, H, D]; falls back to the XLA dense path for shapes that don't
tile (T not divisible by the block size, tiny D).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _largest_divisor(t: int, cap: int) -> int:
    """Largest multiple of 128 that divides ``t`` and is <= ``cap`` (0 if none)."""
    for b in range(min(cap, t) // 128 * 128, 0, -128):
        if t % b == 0:
            return b
    return 0


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct for a pallas output, carrying the union of the
    operands' varying-mesh-axes (vma) so the kernel works inside shard_map
    (ring attention calls it per chunk) as well as at top level."""
    vma = frozenset()
    for x in operands:
        v = getattr(jax.typeof(x), "vma", None)
        if v:
            vma |= v
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax without vma support
        return jax.ShapeDtypeStruct(shape, dtype)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, block_q, block_k,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # Matmuls take the operands at their native dtype (bf16 in → one MXU
        # pass with f32 accumulate); upcasting first would force the slow
        # multi-pass f32 path for bf16 inputs.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            # Rows whose every key is masked: keep them at zero weight.
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip kv blocks that lie entirely above the diagonal — the causal
        # mask would zero every row, so neither matmul needs to run.
        pl.when((qi + 1) * block_q > ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)
        # Row logsumexp for the backward pass, written in the scratch's own
        # lane-replicated (block_q, 128) layout — no in-kernel transpose.
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-30))


def _blockwise_attention(q, k, v, causal, block_q, block_k, return_lse=False):
    """Pure-jax chunked streaming-softmax attention — the differentiable
    reference the backward pass uses (same math as the kernel; O(block)
    score memory thanks to the scan + checkpointed inner step)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = D**-0.5
    nq, nk = Tq // block_q, Tk // block_k
    qb = jnp.moveaxis(
        q.astype(jnp.float32).reshape(B, nq, block_q, H, D), 1, 0
    )  # [nq, B, bq, H, D]
    kb = jnp.moveaxis(k.astype(jnp.float32).reshape(B, nk, block_k, H, D), 1, 0)
    vb = jnp.moveaxis(v.astype(jnp.float32).reshape(B, nk, block_k, H, D), 1, 0)

    def per_q(args):
        qi, q_blk = args  # q_blk [B, bq, H, D]

        def kv_step(carry, inp):
            ki, k_blk, v_blk = inp

            def active(carry):
                from ..parallel.ring_attention import online_softmax_update

                acc, l, m = carry
                s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_blk) * scale
                if causal:
                    q_pos = qi * block_q + jnp.arange(block_q)
                    k_pos = ki * block_k + jnp.arange(block_k)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    s = jnp.where(mask[None, None], s, _NEG_INF)
                return online_softmax_update(
                    s, v_blk, acc, l, m, zero_masked_rows=causal
                )

            if causal:
                # Mirror the kernel's pl.when: kv blocks entirely above the
                # diagonal contribute nothing — skip their matmuls.
                carry = jax.lax.cond(
                    (qi + 1) * block_q > ki * block_k, active, lambda c: c, carry
                )
            else:
                carry = active(carry)
            return carry, None

        init = (
            jnp.zeros((B, H, block_q, D), jnp.float32),
            jnp.zeros((B, H, block_q), jnp.float32),
            jnp.full((B, H, block_q), _NEG_INF, jnp.float32),
        )
        (acc, l, m), _ = jax.lax.scan(
            jax.checkpoint(kv_step), init, (jnp.arange(nk), kb, vb)
        )
        out = acc / jnp.maximum(l[..., None], 1e-30)  # [B, H, bq, D]
        lse = m + jnp.log(jnp.maximum(l, 1e-30))  # [B, H, bq]
        return jnp.moveaxis(out, 1, 2), jnp.moveaxis(lse, 1, 2)

    outs, lses = jax.lax.map(per_q, (jnp.arange(nq), qb))  # [nq, B, bq, ...]
    out = jnp.moveaxis(outs, 0, 1).reshape(B, Tq, H, D).astype(q.dtype)
    if return_lse:
        return out, jnp.moveaxis(lses, 0, 1).reshape(B, Tq, H)
    return out


def _use_oracle_bwd() -> bool:
    return os.environ.get("MOOLIB_TPU_FLASH_BWD", "pallas") == "jax"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse_raw = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse_raw)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if _use_oracle_bwd():
        # Oracle path: VJP of the blockwise-jax formulation (recomputes the
        # streaming softmax in pure XLA; same FLOPs class, O(block) score
        # memory).  Kept for parity testing against the pallas kernels.
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _blockwise_attention(
                q_, k_, v_, causal, block_q, block_k
            ),
            q, k, v,
        )
        return vjp(g)
    return _flash_backward(
        q, k, v, out, lse, g, None, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, causal, block_q, block_k, interpret):
    """Like ``_flash`` but returns (out [B,Tq,H,D], lse [B,Tq,H]) with lse a
    differentiable output: ring attention combines per-chunk results by
    logsumexp weights, so gradients flow through it (the lse cotangent folds
    into the backward kernels' delta term — no extra kernel).  A separate
    custom_vjp so the plain path never materializes/consumes a zero lse
    cotangent on the training hot path."""
    out, lse_raw = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    B, Tq, H, D = q.shape
    return out, lse_raw.reshape(B, H, Tq).transpose(0, 2, 1)


def _flash_lse_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse_raw = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    B, Tq, H, D = q.shape
    lse_pub = lse_raw.reshape(B, H, Tq).transpose(0, 2, 1)
    return (out, lse_pub), (q, k, v, out, lse_raw)


def _flash_lse_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    g_out, g_lse = g
    if _use_oracle_bwd():
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _blockwise_attention(
                q_, k_, v_, causal, block_q, block_k, return_lse=True
            ),
            q, k, v,
        )
        return vjp((g_out, g_lse))
    return _flash_backward(
        q, k, v, out, lse, g_out, g_lse, causal, block_q, block_k, interpret
    )


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _flash_bwd_dq_kernel(
    k_ref, q_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale, causal, block_q, block_k,
):
    """dq pass: one q block per (batch*head, qi), kv blocks stream innermost.

    Works in scores-transposed layout — st = k @ qᵀ is [block_k, block_q] —
    so the per-row lse/delta tables enter as natural (1, 1, block_q) row
    vectors (no sublane→lane transpose anywhere on the TPU).
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        st = jax.lax.dot_general(
            k_ref[0], q_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bk, bq] f32
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, st.shape, 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, st.shape, 1)
            st = jnp.where(q_pos >= k_pos, st, _NEG_INF)
        pt = jnp.exp(st - lse_ref[0])  # masked entries underflow to 0
        dpt = jax.lax.dot_general(
            v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, bq]
        dst = pt * (dpt - delta_ref[0]) * scale
        dq_scr[:] += jax.lax.dot_general(
            dst.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, D]

    if causal:
        pl.when((qi + 1) * block_q > ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    k_ref, q_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, causal, block_q, block_k,
):
    """dk/dv pass: one kv block per (batch*head, ki), q blocks stream innermost.

    Same transposed-scores layout as the dq pass; dk and dv accumulate in
    f32 scratch across the q sweep.
    """
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        st = jax.lax.dot_general(
            k_ref[0], q_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bk, bq]
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, st.shape, 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, st.shape, 1)
            st = jnp.where(q_pos >= k_pos, st, _NEG_INF)
        pt = jnp.exp(st - lse_ref[0])
        dv_scr[:] += jax.lax.dot_general(
            pt.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dpt = jax.lax.dot_general(
            v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dst = pt * (dpt - delta_ref[0]) * scale
        dk_scr[:] += jax.lax.dot_general(
            dst.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]

    if causal:
        pl.when((qi + 1) * block_q > ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, g_lse, causal, block_q, block_k, interpret
):
    """Pallas flash backward: dq pass + dk/dv pass (FlashAttention-2 style).

    ``g_lse`` is the cotangent of the lse output ([B,Tq,H] or None): since
    dL/ds_j = p_j((g·v_j) - (g·out) + g_lse), it folds into the delta row
    table as ``delta - g_lse`` — the kernels are unchanged.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = D**-0.5
    # Backward blocks capped at 512x512 (env-tunable for on-chip sweeps;
    # read at TRACE time — the jit cache does not key on env vars, so a
    # sweep must re-trace per value: fresh process, cleared caches, or AOT
    # .lower().compile() while the var is set, as flash_bench does for
    # MOOLIB_TPU_FLASH_BWD.  Values clamp up to the 128 tile minimum.):
    # the transposed-score intermediates (st, pt, dpt — all [bk, bq] f32)
    # plus two f32 output scratches are live at once, so the forward's
    # 512x1024 tiles would crowd VMEM.  The cap must preserve divisibility
    # (e.g. Tk=1280 forwards with block_k=640; a blind min() would drop the
    # tail kv block) — re-derive the largest dividing block under the cap.
    # Always succeeds: any valid forward block is a multiple of 128
    # dividing T, so 128 divides T.
    cap_q = max(128, int(os.environ.get("MOOLIB_TPU_FLASH_BWD_BLOCK_Q", 512)))
    cap_k = max(128, int(os.environ.get("MOOLIB_TPU_FLASH_BWD_BLOCK_K", 512)))
    bq = _largest_divisor(Tq, min(block_q, cap_q))
    bk = _largest_divisor(Tk, min(block_k, cap_k))

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb, dob = to_bh(q), to_bh(k), to_bh(v), to_bh(g)
    # delta_i = Σ_d dO_i · O_i — row table, like lse, in [B*H, Tq] layout.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1).reshape(B * H, Tq)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32).transpose(0, 2, 1).reshape(
            B * H, Tq
        )

    kwargs = dict(scale=scale, causal=causal, block_q=bq, block_k=bk)
    # The row tables ride as [B*H, 1, T]: TPU lowering constrains the last
    # two block dims (divisible by (8, 128) or equal to the array dims), so
    # a 2-D (1, bq) block over [B*H, T] is illegal when B*H > 1 — the unit
    # dim must sit in the constrained sublane slot, where 1 == 1 passes.
    lse = lse.reshape(B * H, 1, Tq)
    delta = delta.reshape(B * H, 1, Tq)
    row_spec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **kwargs),
        grid=(B * H, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),  # k
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),  # do
            row_spec,  # lse
            row_spec,  # delta
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((B * H, Tq, D), q.dtype, kb, qb, vb, dob, delta),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(kb, qb, vb, dob, lse, delta)

    qrow_spec = pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **kwargs),
        grid=(B * H, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),  # k
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, 0)),  # q
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),  # v
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, j, 0)),  # do
            qrow_spec,  # lse
            qrow_spec,  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((B * H, Tk, D), k.dtype, kb, qb, vb, dob, delta),
            _out_struct((B * H, Tk, D), v.dtype, kb, qb, vb, dob, delta),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(kb, qb, vb, dob, lse, delta)

    def from_bh(x, T):
        return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    return from_bh(dq, Tq), from_bh(dk, Tk), from_bh(dv, Tk)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    return_lse: bool = False,
):
    """Blockwise attention; q/k/v: [B, T, H, D] → [B, T, H, D].

    Differentiable: the forward runs the pallas kernel (also emitting the
    row logsumexp); the backward runs two pallas kernels — a dq pass and a
    dk/dv pass (FlashAttention-2 style) — so the TransformerLM trains
    through on-chip kernels at long T.  ``MOOLIB_TPU_FLASH_BWD=jax``
    selects the blockwise-jax VJP oracle instead (parity testing).

    ``return_lse=True`` additionally returns the per-row logsumexp
    ([B, T, H], f32, differentiable) — the combinable form ring attention
    uses to merge chunk results across ICI hops.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # Defaults from a block sweep on TPU v5e (T=4096, causal): 128x128 blocks
    # leave grid overhead dominant (32k tiny steps, 7.7 ms); 512x1024 runs the
    # same shape in 1.8 ms while q+k+v+s blocks stay well under VMEM.  Use the
    # largest 128-multiple divisor of T up to the tuned size so lengths like
    # 1536 or 2560 still ride the kernel; T without such a divisor (e.g. 250,
    # or 160 < 2*128) takes the dense fallback rather than handing Mosaic a
    # non-tile-aligned block.
    explicit_q = block_q is not None
    explicit_k = block_k is not None
    if block_q is None:
        block_q = _largest_divisor(Tq, 512)
    if block_k is None:
        block_k = _largest_divisor(Tk, 1024)
    # Blocks below the 128-lane tile (T with a large odd factor) aren't worth
    # a pallas launch — use the dense path.  An unusable *caller-supplied*
    # block raises instead (the caller tuning blocks gets a signal, not a
    # silent O(T²) reroute); an unusable auto-selected one keeps the
    # documented silent fallback.
    bad_q = block_q < 128 or block_q % 128 or Tq % block_q
    bad_k = block_k < 128 or block_k % 128 or Tk % block_k
    if (bad_q and explicit_q) or (bad_k and explicit_k):
        raise ValueError(
            f"flash_attention block_q={block_q}, block_k={block_k} unusable for "
            f"Tq={Tq}, Tk={Tk}: blocks must be multiples of 128 that divide the "
            "sequence length. Omit them to auto-select (or fall back to dense)."
        )
    if bad_q or bad_k:
        from ..parallel.ring_attention import dense_attention_lse, full_attention

        if return_lse:
            return dense_attention_lse(q, k, v, causal=causal)
        return full_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if return_lse:
        return _flash_lse(q, k, v, causal, block_q, block_k, interpret)
    return _flash(q, k, v, causal, block_q, block_k, interpret)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = D**-0.5

    # [B, T, H, D] -> [B*H, T, D]
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, Tq // block_q, Tk // block_k)
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((B * H, Tq, D), q.dtype, qb, kb, vb),
            _out_struct((B * H, Tq, 128), jnp.float32, qb, kb, vb),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    # lse comes out lane-replicated; one lane is the [B*H, Tq] row table.
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3), lse[:, :, 0]
