"""Pallas TPU flash attention (single-chip blockwise attention).

The single-chip complement of ``moolib_tpu.parallel.ring_attention``: scores
never materialize in HBM — K/V stream through VMEM in blocks while a running
(max, sum, accumulator) triple folds the softmax (same math as the ring
kernel, here over the *local* sequence).  Written with ``pl.pallas_call``
grid (batch*heads, q-blocks, kv-blocks): the kv axis is innermost so the
output block revisits and the scratch accumulators carry across iterations
(standard TPU pallas accumulation pattern).

The reference framework has no attention at all (SURVEY.md §5.7) — this is
new TPU-idiomatic capability for the long-context side of the framework.

Layout [B, T, H, D]; falls back to the XLA dense path for shapes that don't
tile (T not divisible by the block size, tiny D).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # Matmuls take the operands at their native dtype (bf16 in → one MXU
        # pass with f32 accumulate); upcasting first would force the slow
        # multi-pass f32 path for bf16 inputs.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk] f32

        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if causal:
            # Rows whose every key is masked: keep them at zero weight.
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip kv blocks that lie entirely above the diagonal — the causal
        # mask would zero every row, so neither matmul needs to run.
        pl.when((qi + 1) * block_q > ki * block_k)(_compute)
    else:
        _compute()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _blockwise_attention(q, k, v, causal, block_q, block_k):
    """Pure-jax chunked streaming-softmax attention — the differentiable
    reference the backward pass uses (same math as the kernel; O(block)
    score memory thanks to the scan + checkpointed inner step)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = D**-0.5
    nq, nk = Tq // block_q, Tk // block_k
    qb = jnp.moveaxis(
        q.astype(jnp.float32).reshape(B, nq, block_q, H, D), 1, 0
    )  # [nq, B, bq, H, D]
    kb = jnp.moveaxis(k.astype(jnp.float32).reshape(B, nk, block_k, H, D), 1, 0)
    vb = jnp.moveaxis(v.astype(jnp.float32).reshape(B, nk, block_k, H, D), 1, 0)

    def per_q(args):
        qi, q_blk = args  # q_blk [B, bq, H, D]

        def kv_step(carry, inp):
            ki, k_blk, v_blk = inp

            def active(carry):
                from ..parallel.ring_attention import online_softmax_update

                acc, l, m = carry
                s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_blk) * scale
                if causal:
                    q_pos = qi * block_q + jnp.arange(block_q)
                    k_pos = ki * block_k + jnp.arange(block_k)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    s = jnp.where(mask[None, None], s, _NEG_INF)
                return online_softmax_update(
                    s, v_blk, acc, l, m, zero_masked_rows=causal
                )

            if causal:
                # Mirror the kernel's pl.when: kv blocks entirely above the
                # diagonal contribute nothing — skip their matmuls.
                carry = jax.lax.cond(
                    (qi + 1) * block_q > ki * block_k, active, lambda c: c, carry
                )
            else:
                carry = active(carry)
            return carry, None

        init = (
            jnp.zeros((B, H, block_q, D), jnp.float32),
            jnp.zeros((B, H, block_q), jnp.float32),
            jnp.full((B, H, block_q), _NEG_INF, jnp.float32),
        )
        (acc, l, _), _ = jax.lax.scan(
            jax.checkpoint(kv_step), init, (jnp.arange(nk), kb, vb)
        )
        out = acc / jnp.maximum(l[..., None], 1e-30)  # [B, H, bq, D]
        return jnp.moveaxis(out, 1, 2)  # [B, bq, H, D]

    outs = jax.lax.map(per_q, (jnp.arange(nq), qb))  # [nq, B, bq, H, D]
    return (
        jnp.moveaxis(outs, 0, 1).reshape(B, Tq, H, D).astype(q.dtype)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    # Backward = VJP of the blockwise-jax formulation (recomputes the
    # streaming softmax; same FLOPs class as a flash backward, O(block)
    # score memory).  The pallas forward computes the same function up to
    # float rounding, so these are the gradients of flash attention.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_attention(q_, k_, v_, causal, block_q, block_k),
        q,
        k,
        v,
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
):
    """Blockwise attention; q/k/v: [B, T, H, D] → [B, T, H, D].

    Differentiable: the forward runs the pallas kernel; the backward is the
    VJP of an equivalent blockwise-jax formulation (``custom_vjp``), so the
    TransformerLM trains through this path at long T.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    # Defaults from a block sweep on TPU v5e (T=4096, causal): 128x128 blocks
    # leave grid overhead dominant (32k tiny steps, 7.7 ms); 512x1024 runs the
    # same shape in 1.8 ms while q+k+v+s blocks stay well under VMEM.  Use the
    # largest 128-multiple divisor of T up to the tuned size so lengths like
    # 1536 or 2560 still ride the kernel; T without such a divisor (e.g. 250,
    # or 160 < 2*128) takes the dense fallback rather than handing Mosaic a
    # non-tile-aligned block.
    def _largest_divisor(t, cap):
        for b in range(min(cap, t) // 128 * 128, 0, -128):
            if t % b == 0:
                return b
        return 0

    if block_q is None:
        block_q = _largest_divisor(Tq, 512)
    if block_k is None:
        block_k = _largest_divisor(Tk, 1024)
    # Blocks below the 128-lane tile (T with a large odd factor) aren't worth
    # a pallas launch — use the dense path.
    if block_q < 128 or block_k < 128 or Tq % block_q or Tk % block_k:
        from ..parallel.ring_attention import full_attention

        return full_attention(q, k, v, causal=causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash(q, k, v, causal, block_q, block_k, interpret)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = D**-0.5

    # [B, T, H, D] -> [B*H, T, D]
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    grid = (B * H, Tq // block_q, Tk // block_k)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
