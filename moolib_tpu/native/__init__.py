"""Native (C++) runtime components with build-on-first-use and fallbacks.

The reference's runtime is C++ throughout (SURVEY.md §2.1); this package
holds the moolib_tpu equivalents:

- ``_moolib_codec``: CPython-extension message codec (tag-based encoding,
  out-of-band zero-copy arrays, pickle fallback, jax host-staging hook) —
  counterpart of ``src/serialization.h`` + ``src/pythonserialization.h``.
- ``libmoolib_shmq``: futex semaphores + SPSC rings in fork-shared memory
  (ctypes) — counterpart of ``src/shm.h``'s SharedSemaphore/SharedQueue.

Sources live in ``<repo>/native/``; they are compiled with g++ on first use
into ``~/.cache/moolib_tpu`` (or $MOOLIB_TPU_CACHE). Every consumer treats
these as accelerators: if a compiler is missing the pure-python paths are
used and everything still works.
"""

from __future__ import annotations

import ctypes
import hashlib
import importlib.util
import os
import subprocess
import sys
import sysconfig
from typing import Optional

from .. import utils

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native")


def _cache_dir() -> str:
    d = os.environ.get("MOOLIB_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "moolib_tpu"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _source_hash(path: str) -> str:
    """Cache tag for a built artifact: source hash + sanitize mode (a
    sanitized build must never be picked up by a normal run or vice versa)."""
    with open(path, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    san = os.environ.get("MOOLIB_TPU_SANITIZE")
    return f"{tag}-{san}" if san else tag


def _build(src: str, out: str, extra_flags=()) -> bool:
    # MOOLIB_TPU_SANITIZE=thread|address builds every native component with
    # the given sanitizer (run python under the matching LD_PRELOAD runtime;
    # see tests/test_native_sanitizers.py and docs/STATUS.md for the recipe).
    san = os.environ.get("MOOLIB_TPU_SANITIZE")
    san_flags = (f"-fsanitize={san}",) if san else ()
    cmd = [
        "g++",
        "-O2",
        "-g",
        "-std=c++17",
        "-shared",
        "-fPIC",
        src,
        "-o",
        out,
        *san_flags,
        *extra_flags,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
    except (OSError, subprocess.TimeoutExpired) as e:
        utils.log_error("native build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        utils.log_error("native build failed:\n%s", proc.stderr[-4000:])
        return False
    return True


def _load_codec():
    src = os.path.join(_SRC_DIR, "codec.cc")
    if not os.path.exists(src):
        return None
    tag = _source_hash(src)
    out = os.path.join(_cache_dir(), f"_moolib_codec_{tag}.so")
    if not os.path.exists(out):
        import numpy as np

        py_inc = sysconfig.get_paths()["include"]
        np_inc = np.get_include()
        # Per-process tmp name: concurrent first-use builds must not
        # interleave writes; os.replace makes the install atomic.
        tmp = f"{out}.{os.getpid()}.tmp"
        ok = _build(src, tmp, (f"-I{py_inc}", f"-I{np_inc}"))
        if not ok:
            return None
        os.replace(tmp, out)
    spec = importlib.util.spec_from_file_location("_moolib_codec", out)
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception as e:  # noqa: BLE001
        utils.log_error("native codec load failed: %s", e)
        return None
    return mod


def _load_shmq():
    src = os.path.join(_SRC_DIR, "shmq.cc")
    if not os.path.exists(src):
        return None
    tag = _source_hash(src)
    out = os.path.join(_cache_dir(), f"libmoolib_shmq_{tag}.so")
    if not os.path.exists(out):
        tmp = f"{out}.{os.getpid()}.tmp"
        if not _build(src, tmp):
            return None
        os.replace(tmp, out)
    try:
        lib = ctypes.CDLL(out)
    except OSError as e:
        utils.log_error("native shmq load failed: %s", e)
        return None
    lib.moolib_sem_size.restype = ctypes.c_size_t
    lib.moolib_sem_init.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.moolib_sem_post.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.moolib_sem_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.moolib_sem_wait.restype = ctypes.c_int
    lib.moolib_sem_value.argtypes = [ctypes.c_void_p]
    lib.moolib_sem_value.restype = ctypes.c_int32
    lib.moolib_ring_size.argtypes = [ctypes.c_uint32]
    lib.moolib_ring_size.restype = ctypes.c_size_t
    lib.moolib_ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.moolib_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
    lib.moolib_ring_push.restype = ctypes.c_int
    lib.moolib_ring_pop.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
    ]
    lib.moolib_ring_pop.restype = ctypes.c_int
    return lib


_codec = None
_codec_tried = False
_shmq = None
_shmq_tried = False


def get_codec():
    """The native codec module, or None (fallback to python serialization)."""
    global _codec, _codec_tried
    if not _codec_tried:
        _codec_tried = True
        if os.environ.get("MOOLIB_TPU_NO_NATIVE") == "1":
            return None
        _codec = _load_codec()
        if _codec is not None:
            _register_jax(_codec)
    return _codec


def _register_jax(codec_mod) -> None:
    import jax
    import numpy as np

    def to_numpy(x):
        return np.asarray(x)

    import jax.numpy as jnp

    def from_numpy(x):
        # device_put can zero-copy alias a host numpy buffer (CPU backend);
        # a view over the transient receive buffer must be copied to an
        # owning array first — jax keeps THAT alive.
        if not x.flags.owndata:
            x = x.copy()
        return jnp.asarray(x)

    codec_mod.register_jax(jax.Array, to_numpy, from_numpy)


def get_shmq():
    """The native shm/futex library, or None (fallback to multiprocessing)."""
    global _shmq, _shmq_tried
    if not _shmq_tried:
        _shmq_tried = True
        if os.environ.get("MOOLIB_TPU_NO_NATIVE") == "1":
            return None
        _shmq = _load_shmq()
    return _shmq


class NativeSemaphore:
    """Counting semaphore placed in caller-provided shared memory."""

    def __init__(self, lib, addr: int, initialize: bool = True, initial: int = 0):
        self._lib = lib
        self._addr = addr
        if initialize:
            lib.moolib_sem_init(addr, initial)

    @staticmethod
    def size(lib) -> int:
        return lib.moolib_sem_size()

    def release(self, n: int = 1) -> None:
        self._lib.moolib_sem_post(self._addr, n)

    def acquire(self, timeout: Optional[float] = None) -> bool:
        # The C call returns -2 on EINTR so control comes back to python and
        # pending signal handlers (KeyboardInterrupt) run between retries.
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = -1 if deadline is None else max(0, int((deadline - _time.monotonic()) * 1000))
            rc = self._lib.moolib_sem_wait(self._addr, remaining)
            if rc == 0:
                return True
            if rc == -1:
                return False
            # rc == -2: interrupted; loop (python checks signals here)


class NativeRing:
    """SPSC int32 ring queue in caller-provided shared memory."""

    def __init__(self, lib, addr: int, capacity: int, initialize: bool = True):
        self._lib = lib
        self._addr = addr
        if initialize:
            lib.moolib_ring_init(addr, capacity)

    @staticmethod
    def size(lib, capacity: int) -> int:
        return lib.moolib_ring_size(capacity)

    def push(self, value: int, timeout: Optional[float] = None) -> bool:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = -1 if deadline is None else max(0, int((deadline - _time.monotonic()) * 1000))
            rc = self._lib.moolib_ring_push(self._addr, value, remaining)
            if rc == 0:
                return True
            if rc == -1:
                return False
            # EINTR: retry, letting python signal handlers run

    def pop(self, timeout: Optional[float] = None) -> Optional[int]:
        import time as _time

        out = ctypes.c_int32()
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = -1 if deadline is None else max(0, int((deadline - _time.monotonic()) * 1000))
            rc = self._lib.moolib_ring_pop(self._addr, ctypes.byref(out), remaining)
            if rc == 0:
                return out.value
            if rc == -1:
                return None
            # EINTR: retry
