"""ctypes binding for the native epoll transport (``native/transport.cc``).

One ``NativeNet`` per ``Rpc``: a C++ epoll thread owns every socket; Python
gets whole frames via callbacks (invoked on the epoll thread — the Rpc
marshals them onto its own engine thread).  Counterpart of the reference's
``poll::PollThread`` + ``ipc::Connection`` framing
(``src/transports/socket.cc:861-955``, ``src/transports/ipc.cc:51-232``).
"""

from __future__ import annotations

import ctypes
import os
import weakref
from typing import Callable, Optional

import numpy as np

from . import _build, _cache_dir, _source_hash, _SRC_DIR
from .. import utils

ACCEPT_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p)
FRAME_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,
    ctypes.c_int64,
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int32,
)
CLOSE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int64)
CONNECT_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64)
RELEASE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int64)

_lib = None
_lib_tried = False


def _marshal_chunks(chunks):
    """Byte-like chunks → (ctypes bufs, lens, keep-alive objects)."""
    n = len(chunks)
    bufs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    keep = []
    for i, c in enumerate(chunks):
        if isinstance(c, bytes):
            keep.append(c)
            bufs[i] = ctypes.cast(ctypes.c_char_p(c), ctypes.c_void_p)
            lens[i] = len(c)
        else:
            mv = memoryview(c)
            if mv.ndim != 1 or mv.format != "B":
                mv = mv.cast("B")
            arr = np.frombuffer(mv, np.uint8)
            keep.append(arr)
            bufs[i] = ctypes.c_void_p(arr.ctypes.data)
            lens[i] = arr.nbytes
    return bufs, lens, keep


def get_lib():
    """The native transport library, or None (fallback to asyncio)."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("MOOLIB_TPU_NO_NATIVE") == "1":
        return None
    src = os.path.join(_SRC_DIR, "transport.cc")
    if not os.path.exists(src):
        return None
    tag = _source_hash(src)
    out = os.path.join(_cache_dir(), f"libmoolib_net_{tag}.so")
    if not os.path.exists(out):
        tmp = f"{out}.{os.getpid()}.tmp"
        if not _build(src, tmp, ("-pthread",)):
            return None
        os.replace(tmp, out)
    try:
        lib = ctypes.CDLL(out)
    except OSError as e:
        utils.log_error("native transport load failed: %s", e)
        return None
    lib.moolib_net_create.restype = ctypes.c_void_p
    lib.moolib_net_create.argtypes = [
        ACCEPT_CB,
        FRAME_CB,
        CLOSE_CB,
        CONNECT_CB,
        RELEASE_CB,
        ctypes.c_void_p,
    ]
    lib.moolib_net_listen_tcp.restype = ctypes.c_int
    lib.moolib_net_listen_tcp.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.moolib_net_listen_unix.restype = ctypes.c_int
    lib.moolib_net_listen_unix.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.moolib_net_connect_tcp.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int,
    ]
    lib.moolib_net_connect_unix.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p]
    lib.moolib_net_send.restype = ctypes.c_int
    lib.moolib_net_send.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    lib.moolib_net_send_iov.restype = ctypes.c_int
    lib.moolib_net_send_iov.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
        ctypes.c_int64,
    ]
    lib.moolib_net_send_memfd.restype = ctypes.c_int
    lib.moolib_net_send_memfd.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
    ]
    lib.moolib_net_send_memfd_multi.restype = ctypes.c_int32
    lib.moolib_net_send_memfd_multi.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int32,
    ]
    lib.moolib_net_adopt.restype = ctypes.c_int64
    lib.moolib_net_adopt.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.moolib_net_unmap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.moolib_net_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.moolib_net_conn_rx.restype = ctypes.c_uint64
    lib.moolib_net_conn_rx.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.moolib_net_conn_tx.restype = ctypes.c_uint64
    lib.moolib_net_conn_tx.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.moolib_net_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


class NativeNet:
    """One native socket engine. Callbacks fire on the C++ epoll thread;
    callers must marshal onto their own thread and must NOT call
    ``destroy()`` from inside a callback (it joins the epoll thread)."""

    def __init__(
        self,
        on_accept: Callable[[int, str], None],
        on_frame: Callable[[int, bytes], None],
        on_close: Callable[[int], None],
        on_connect: Callable[[int, int], None],
    ):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native transport unavailable")
        self._lib = lib

        # The CFUNCTYPE objects must outlive the engine: keep them on self.
        def _accept(ud, conn_id, transport):
            on_accept(conn_id, transport.decode())

        def _frame(ud, conn_id, datas, lens, n):
            # One callback per burst of frames (a single GIL acquisition
            # covers the whole batch).  Small frames are snapshotted with one
            # string_at memcpy — much cheaper than building a ctypes view
            # and free of lifetime constraints.  Large frames stay zero-copy
            # into the engine's read buffer, valid only for the duration of
            # this callback — consumers deserialize synchronously (array
            # leaves are copied during materialization).
            for i in range(n):
                length = lens[i]
                if length < 65536:
                    view = ctypes.string_at(datas[i], length) if length else b""
                else:
                    view = memoryview(
                        (ctypes.c_ubyte * length).from_address(datas[i])
                    ).cast("B")
                on_frame(conn_id, view)

        def _close(ud, conn_id):
            on_close(conn_id)

        def _connect(ud, req_id, conn_id):
            on_connect(req_id, conn_id)

        def _release(ud, token):
            # Unpin the buffers of a fully-written (or dropped) frame.
            self._pinned.pop(token, None)

        self._pinned: dict = {}
        self._token_counter = iter(range(1, 2**62))
        self.memfd_sends = 0  # frames that rode the zero-copy memfd path
        self._acb = ACCEPT_CB(_accept)
        self._fcb = FRAME_CB(_frame)
        self._ccb = CLOSE_CB(_close)
        self._ncb = CONNECT_CB(_connect)
        self._rcb = RELEASE_CB(_release)
        self._ctx = lib.moolib_net_create(
            self._acb, self._fcb, self._ccb, self._ncb, self._rcb, None
        )
        if not self._ctx:
            raise RuntimeError("moolib_net_create failed")

    def listen_tcp(self, host: str, port: int) -> int:
        """Returns the bound port (0 in ``port`` picks one), or raises."""
        if not self._ctx:
            raise OSError("engine destroyed")
        r = self._lib.moolib_net_listen_tcp(self._ctx, host.encode(), port)
        if r < 0:
            raise OSError(f"listen failed on {host}:{port}")
        return r

    def listen_unix(self, path: str) -> None:
        if not self._ctx:
            raise OSError("engine destroyed")
        if self._lib.moolib_net_listen_unix(self._ctx, path.encode()) < 0:
            raise OSError(f"listen failed on {path}")

    def connect_tcp(self, req_id: int, host: str, port: int) -> None:
        if self._ctx:
            self._lib.moolib_net_connect_tcp(self._ctx, req_id, host.encode(), port)

    def connect_unix(self, req_id: int, path: str) -> None:
        if self._ctx:
            self._lib.moolib_net_connect_unix(self._ctx, req_id, path.encode())

    def send(self, conn_id: int, data) -> bool:
        """Queue one frame (the engine adds the length prefix). Any thread."""
        if not self._ctx:
            return False
        if not isinstance(data, bytes):
            data = bytes(data)
        return self._lib.moolib_net_send(self._ctx, conn_id, data, len(data)) == 0

    def send_iov(self, conn_id: int, chunks) -> bool:
        """Gather-send one frame from byte-like chunks — the analogue of the
        reference's iovec sends. Small chunks are copied into the engine;
        large ones ride zero-copy, pinned here until the engine reports the
        frame written (release callback). Callers must treat large chunk
        buffers as immutable until then (same contract as the reference's
        refcounted tensor buffers on the wire)."""
        if not self._ctx:
            return False
        # Small frames: one join + one primitive-args ctypes call — the
        # iov/pin machinery costs ~15us per call, pure overhead below the
        # zero-copy threshold where nothing can pin anyway.
        total = 0
        for c in chunks:
            total += len(c) if isinstance(c, bytes) else memoryview(c).nbytes
            if total >= 65536:
                break
        if total < 65536:
            data = b"".join(
                c if isinstance(c, bytes) else bytes(c) for c in chunks
            )
            return self._lib.moolib_net_send(self._ctx, conn_id, data, len(data)) == 0
        # keep: buffer-exporting objects; pinned if the engine borrows.
        bufs, lens, keep = _marshal_chunks(chunks)
        token = next(self._token_counter)
        # Publish the pin before the call: the epoll thread can finish the
        # write (and fire release) before moolib_net_send_iov returns.
        self._pinned[token] = keep
        rc = self._lib.moolib_net_send_iov(
            self._ctx, conn_id, bufs, lens, len(chunks), token
        )
        if rc != 1:  # fully copied (or error): nothing stays borrowed
            self._pinned.pop(token, None)
        # rc -2 = conn unknown/closed at the engine: the frame did NOT go
        # out — callers must treat it as a dead connection, not a success.
        return rc >= 0

    def send_memfd(self, conn_id: int, chunks) -> bool:
        """Same-host zero-copy send: the frame payload is written into an
        anonymous memfd; only a 12-byte control frame + the fd (SCM_RIGHTS)
        cross the unix socket, and the receiver mmaps the payload. The write
        into the memfd completes synchronously, so nothing is pinned."""
        if not self._ctx:
            return False
        bufs, lens, keep = _marshal_chunks(chunks)
        ok = (
            self._lib.moolib_net_send_memfd(self._ctx, conn_id, bufs, lens, len(chunks))
            == 0
        )
        del keep  # the memfd write completed synchronously inside the call
        if ok:
            self.memfd_sends += 1
        return ok

    def send_memfd_multi(self, conn_ids, chunks) -> int:
        """Multicast one frame to several same-host peers: the payload is
        written into ONE anonymous memfd and a dup of the fd rides to every
        connection (receivers mmap the same pages).  Returns how many
        connections the frame was queued to — the caller retries the missed
        ones individually (receiver-side rid dedup makes that safe).  The
        write completes synchronously, nothing is pinned."""
        if not self._ctx or not conn_ids:
            return 0
        bufs, lens, keep = _marshal_chunks(chunks)
        ids = (ctypes.c_int64 * len(conn_ids))(*conn_ids)
        sent = self._lib.moolib_net_send_memfd_multi(
            self._ctx, ids, len(conn_ids), bufs, lens, len(chunks)
        )
        del keep
        if sent:
            self.memfd_sends += sent
        return int(sent)

    def adopt_frame(self, frame) -> "np.ndarray | None":
        """Adopt the memfd mapping behind ``frame`` (a zero-copy memoryview
        delivered by the CURRENT frame callback, on the callback thread):
        ownership of the pages transfers here, and the returned uint8 array
        stays valid for its own lifetime — munmap runs when the array is
        garbage collected.  Returns None when the frame is not an adoptable
        mapping (small copied frames, TCP frames, asyncio transport)."""
        if not self._ctx or not isinstance(frame, memoryview):
            return None
        obj = frame.obj
        if not isinstance(obj, ctypes.Array):
            return None
        addr = ctypes.addressof(obj)
        size = self._lib.moolib_net_adopt(self._ctx, ctypes.c_void_p(addr))
        if size < 0:
            return None
        arr_t = (ctypes.c_ubyte * size).from_address(addr)
        out = np.frombuffer(arr_t, np.uint8)
        # The mapping is PROT_READ; numpy must not let anyone write into it.
        out.flags.writeable = False
        weakref.finalize(arr_t, self._lib.moolib_net_unmap,
                         ctypes.c_void_p(addr), size)
        return out

    def close_conn(self, conn_id: int) -> None:
        if self._ctx:
            self._lib.moolib_net_close_conn(self._ctx, conn_id)

    def conn_rx(self, conn_id: int) -> int:
        """Monotonic received-byte count for a live connection (0 if gone)."""
        if not self._ctx:
            return 0
        return self._lib.moolib_net_conn_rx(self._ctx, conn_id)

    def conn_tx(self, conn_id: int) -> int:
        """Monotonic written-byte count for a live connection (0 if gone)."""
        if not self._ctx:
            return 0
        return self._lib.moolib_net_conn_tx(self._ctx, conn_id)

    def destroy(self) -> None:
        ctx, self._ctx = self._ctx, None
        if ctx:
            self._lib.moolib_net_destroy(ctx)
