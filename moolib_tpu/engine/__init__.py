"""Continuous-batching serving engine over a paged KV cache.

The serving-throughput subsystem (ROADMAP item 2): slot-scheduled decode
against a device-resident KV block pool, slotting in UNDER the existing
``serving.ServeService`` contract so ``lm_serve --engine`` is a drop-in arm
next to the batch-synchronous baseline.  See ``engine.py`` for the
slot/block lifecycle and ``ops/paged_attention.py`` for the kernel.
"""

from .engine import ContinuousBatchingEngine, NoFreeSlot  # noqa: F401
from .kv_pool import BlockPool, PoolExhausted  # noqa: F401
from .service import EngineService  # noqa: F401

__all__ = [
    "BlockPool",
    "ContinuousBatchingEngine",
    "EngineService",
    "NoFreeSlot",
    "PoolExhausted",
]
