"""Host-side free-list allocator for the device KV block pool.

The pool itself is device memory (the ``pool_k``/``pool_v`` cache arrays in
the paged decode model — see ``ops.paged_attention``); this class only tracks
which block *ids* are in use.  Blocks are fixed-size (``block_size`` tokens),
so allocation is O(1) list ops with zero external fragmentation — the only
waste is internal (the tail of a sequence's last block), which the engine
accounts as ``serve_pad_tokens_total``.

Block id 0 is reserved as the null block: never allocated, the scatter
target for inactive slots in the fixed-shape decode step.
"""

from __future__ import annotations

from typing import List


class PoolExhausted(RuntimeError):
    """No free blocks — the caller should keep the request queued."""


class BlockPool:
    """Free-list over ``num_blocks`` fixed-size blocks (id 0 reserved)."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (id 0 is reserved), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-used first (their pool
        # rows are the most likely to still be in cache/HBM-near memory).
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._allocated: set = set()

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold ``tokens`` cache entries."""
        return -(-max(int(tokens), 1) // self.block_size)

    def available(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return len(self._allocated)

    def alloc(self, n: int) -> List[int]:
        """Pop ``n`` block ids; raises :class:`PoolExhausted` (allocating
        nothing) when fewer than ``n`` are free."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(pool {self.num_blocks}, block_size {self.block_size})"
            )
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the free list.  Double-free and foreign ids are
        bugs in the caller's slot bookkeeping — raise, don't corrupt."""
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(f"free of unallocated block {b}")
            self._allocated.discard(b)
            self._free.append(b)

    def check_invariants(self) -> None:
        """allocated + free + the null block account for every block exactly
        once (tests call this after randomized alloc/free schedules)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("duplicate ids on the free list")
        if free & self._allocated:
            raise AssertionError("block both free and allocated")
        if 0 in free or 0 in self._allocated:
            raise AssertionError("null block 0 escaped reservation")
        total = len(free) + len(self._allocated) + 1
        if total != self.num_blocks:
            raise AssertionError(
                f"leak: {len(free)} free + {len(self._allocated)} allocated "
                f"+ 1 null != {self.num_blocks}"
            )

    def stats(self) -> dict:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "free": len(self._free),
            "in_use": len(self._allocated),
            "utilization": len(self._allocated) / max(1, self.num_blocks - 1),
        }
