"""EngineService: continuous batching under the ServeService contract.

Subclasses :class:`..serving.ServeService` so the whole resilience surface
is inherited unchanged — bounded admission with typed overload rejects,
req-id dedup, per-request deadlines, ``{name}_stats``, and staged weights —
while the service loop is replaced: instead of take-a-batch / run-to-the-
longest, each iteration drains admitted requests into free decode slots
(prefill + join) and advances ALL occupied slots by one fixed-shape decode
step.  Hot swaps still land between iterations (here: between decode
steps); in-flight sequences continue under the new weights.

The admission controller runs in per-token units: the wait estimate is
``(queued budgets + active remaining budgets) * EMA seconds-per-token``,
which tracks the engine's actual service rate far better than a per-batch
EMA ever could (a "batch" is no longer the unit of service).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Tuple

import numpy as np

from ..rpc import Rpc
from ..serving import (
    AdmissionController,
    ServeService,
    _M_DEPTH,
    _M_PHASE,
    _Request,
)
from .engine import ContinuousBatchingEngine, NoFreeSlot
from .kv_pool import PoolExhausted


class EngineService(ServeService):
    """See module docstring.  ``step_fn``/``params`` of the base class are
    unused (the engine owns the model); everything else — admission, dedup,
    hot-swap staging, stats, close — is the inherited contract."""

    def __init__(self, rpc: Rpc, engine: ContinuousBatchingEngine, *,
                 name: str = "generate", version: int = 0,
                 max_queue: int = 128, dedup_ttl: float = 60.0,
                 default_max_new: int = 16):
        super().__init__(
            rpc, None, None, name=name, version=version,
            batch_size=engine.slots, max_queue=max_queue,
            dedup_ttl=dedup_ttl, default_max_new=default_max_new,
        )
        self._engine = engine
        self._slot_req: Dict[int, _Request] = {}
        # Per-token admission: pending_tokens is called under self._lock
        # (from admit/estimate_wait inside _on_request) — it only reads.
        self.admission = AdmissionController(
            max_queue=max_queue, per_token=True,
            pending_tokens=self._pending_tokens,
        )

    def _pending_tokens(self) -> int:
        queued = sum(
            (r.max_new if r.max_new else self._default_max_new)
            for r in self._queue
        )
        return queued + self._engine.pending_decode_tokens()

    # ------------------------------------------------------------------ swap
    def _maybe_swap_locked(self) -> None:
        before = self._version
        super()._maybe_swap_locked()
        if self._version != before:
            # Between-iteration cutover: the engine re-places the weights;
            # slot state and KV pools are untouched, in-flight sequences
            # finish under the new version.
            self._engine.set_params(self._params)

    # ------------------------------------------------------------------ loop
    def _take_one_locked(self) -> Tuple[str, _Request]:
        """Pop the queue head if the engine can take it.  Returns
        ("none", _) on empty/full, ("join", req) to prefill, ("error", req)
        for shapes the engine cannot serve."""
        if self._closed or not self._queue:
            return "none", None
        req = self._queue[0]
        if req.prompt.shape[0] != 1:
            self._queue.pop(0)
            self._note_take_locked(req)
            return "error", req
        tp = int(req.prompt.shape[1])
        mn = req.max_new if req.max_new else self._default_max_new
        if not self._engine.can_accept(tp, mn):
            return "none", None
        self._queue.pop(0)
        self._note_take_locked(req)
        return "join", req

    def _note_take_locked(self, req: _Request) -> None:
        _M_DEPTH.dec()
        wait = time.monotonic() - req.t_enq
        s = self._stats
        s["takes"] += 1
        s["items"] += 1
        s["wait_s_sum"] += wait
        s["wait_s_max"] = max(s["wait_s_max"], wait)
        _M_PHASE.observe(wait, phase="queue")
        self._note_queue_wait(wait)

    def _admit_joins(self) -> Tuple[int, int]:
        """Drain admitted requests into free slots (prefill + join), oldest
        first — FIFO order is part of the latency contract.  Stops at the
        first request the engine cannot take (slots or blocks full).
        Returns ``(joined, answered)``: requests that entered a slot, and
        requests already answered (prefill-finished or failed).  Accounting
        lands BEFORE the response goes out — a client that sees its reply
        and immediately reads ``{name}_stats`` must see itself counted."""
        joined = answered = 0
        while True:
            with self._lock:
                kind, req = self._take_one_locked()
            if kind == "none":
                return joined, answered
            if kind == "error":
                self._count_answered(1)
                self._respond(
                    req, None,
                    "generate failed: the engine serves single-row prompts "
                    "(got a multi-row request)",
                )
                answered += 1
                continue
            mn = req.max_new if req.max_new else self._default_max_new
            t0 = time.monotonic()
            try:
                slot, emitted = self._engine.submit(req.prompt[0], mn)
            except (NoFreeSlot, PoolExhausted):
                # Raced capacity away (shouldn't happen single-threaded,
                # but stay loss-free): back to the head of the queue.
                with self._lock:
                    self._queue.insert(0, req)
                    _M_DEPTH.inc()
                return joined, answered
            except Exception as e:  # noqa: BLE001 — a poisoned request
                self._count_answered(1)
                self._respond(req, None, f"generate failed: {e}")  # fails alone
                answered += 1
                continue
            _M_PHASE.observe(time.monotonic() - t0, phase="prefill")
            if slot is None:
                # Finished at prefill (budget 1 / immediate EOS).
                self._count_answered(1)
                self._finish(req, emitted)
                answered += 1
            else:
                self._slot_req[slot] = req
                joined += 1

    def _count_answered(self, n: int) -> None:
        self._stats["served"] += n
        self._note_answered(n)

    def _finish(self, req: _Request, emitted: List[int]) -> None:
        out = np.concatenate(
            [req.prompt[0].astype(np.int32), np.asarray(emitted, np.int32)]  # mtlint: allow-host-sync(emitted is a host List[int])
        )
        self._respond(req, out if req.single else out[None], None)

    async def loop(self, total=None) -> int:
        """Serve until ``total`` requests have been answered (None =
        forever).  Returns the number of decode iterations — with mixed
        budgets this is far below baseline's requests x max-budget steps,
        which is the engine's whole throughput story."""
        self._loop = asyncio.get_event_loop()
        self._wake = asyncio.Event()
        served = 0
        eng = self._engine
        try:
            while not self._closed and (total is None or served < total):
                with self._lock:
                    self._maybe_swap_locked()
                    self._sweep_done_locked(time.monotonic())
                _joined, answered = self._admit_joins()
                if not eng.active_count():
                    if answered:
                        served += answered
                        continue
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
                    self._wake.clear()
                    # Idle tick: let the serve_qps window close at zero and
                    # the wait EMA decay, so the autoscaler's idle-shrink
                    # signal sees true silence instead of the last busy
                    # spell's frozen gauges.
                    self._note_answered(0)
                    if not self._queue:
                        self._note_queue_wait(0.0)
                    continue
                t0 = time.monotonic()
                emissions, finished = eng.step()
                dt = time.monotonic() - t0
                if emissions:
                    self.admission.note_service(dt, tokens=len(emissions))
                    _M_PHASE.observe(dt, phase="device")
                self._stats["iterations"] += 1
                done = [(self._slot_req.pop(s), eng.retire(s))
                        for s in finished]
                if done:
                    self._count_answered(len(done))
                for req, toks in done:
                    self._finish(req, toks)
                served += answered + len(done)
                # Yield so RPC callbacks and swap stagings interleave
                # between decode steps.
                await asyncio.sleep(0)
        finally:
            self._loop = None
            self._wake = None
        return self._stats["iterations"]

    # ----------------------------------------------------------------- stats
    def stats(self):
        out = super().stats()
        out["engine"] = self._engine.stats()
        out["ema_token_seconds"] = self.admission.ema_batch_seconds()
        return out

    def close(self) -> None:
        with self._lock:
            inflight = dict(self._slot_req)
            self._slot_req.clear()
        super().close()
        for req in inflight.values():
            try:
                self._respond(req, None, f"serve {self._name}: closed")
            except Exception:  # noqa: BLE001
                pass
